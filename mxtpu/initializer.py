"""Weight initializers (ref: python/mxnet/initializer.py — registry + Xavier/MSRA/
Orthogonal/Bilinear/LSTMBias/… and the InitDesc-pattern dispatch by name)."""
from __future__ import annotations

import json
import math
import re

import jax
import jax.numpy as jnp
import numpy as _np

from .base import MXNetError
from .ndarray import NDArray
from .random import next_key

_REGISTRY = {}


def register(klass):
    _REGISTRY[klass.__name__.lower()] = klass
    # string aliases matching mx.init.create names (ref: mxnet uses 'zeros'/'ones')
    _ALIASES = {"zero": "zeros", "one": "ones"}
    alias = _ALIASES.get(klass.__name__.lower())
    if alias:
        _REGISTRY[alias] = klass
    return klass


class InitDesc(str):
    """Parameter name + attrs hint (ref: initializer.py:InitDesc)."""

    def __new__(cls, name, attrs=None, global_init=None):
        obj = super().__new__(cls, name)
        obj.attrs = attrs or {}
        obj.global_init = global_init
        return obj


class Initializer:
    """Base initializer with the reference's name-pattern dispatch
    (initializer.py:Initializer.__call__): *weight → _init_weight, *bias → zeros,
    *gamma → ones, *beta/ *moving_mean → zeros, *moving_var → ones."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, desc, arr: NDArray):
        if not isinstance(desc, str):
            raise TypeError("desc must be str/InitDesc")
        init = getattr(desc, "attrs", {}).get("__init__", "")
        if init:
            create(init)._init_weight(desc, arr)
            return
        name = desc.lower()
        if name.endswith("weight"):
            self._init_weight(desc, arr)
        elif name.endswith("bias"):
            self._init_zero(desc, arr)
        elif name.endswith("gamma"):
            self._init_one(desc, arr)
        elif name.endswith("beta"):
            self._init_zero(desc, arr)
        elif name.endswith("running_mean") or name.endswith("moving_mean"):
            self._init_zero(desc, arr)
        elif name.endswith("running_var") or name.endswith("moving_var"):
            self._init_one(desc, arr)
        elif name.endswith("min") or name.endswith("max"):
            self._init_zero(desc, arr)
        else:
            self._init_default(desc, arr)

    # -- primitive fills --------------------------------------------------
    def _init_zero(self, _, arr):
        arr._set_data(jnp.zeros(arr.shape, arr._data.dtype))

    def _init_one(self, _, arr):
        arr._set_data(jnp.ones(arr.shape, arr._data.dtype))

    def _init_weight(self, _, arr):  # pragma: no cover - abstract
        raise NotImplementedError

    def _init_default(self, desc, arr):
        self._init_weight(desc, arr)

    def __eq__(self, other):
        return type(self) is type(other) and self._kwargs == other._kwargs


@register
class Zero(Initializer):
    def _init_weight(self, _, arr):
        self._init_zero(_, arr)


@register
class One(Initializer):
    def _init_weight(self, _, arr):
        self._init_one(_, arr)


@register
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, _, arr):
        arr._set_data(jnp.full(arr.shape, self.value, arr._data.dtype))


@register
class Uniform(Initializer):
    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, _, arr):
        arr._set_data(jax.random.uniform(next_key(), arr.shape, jnp.float32,
                                         -self.scale, self.scale).astype(arr._data.dtype))


@register
class Normal(Initializer):
    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, _, arr):
        arr._set_data((jax.random.normal(next_key(), arr.shape) * self.sigma)
                      .astype(arr._data.dtype))


@register
class Xavier(Initializer):
    """Ref: initializer.py:Xavier (factor_type in/out/avg × uniform/gaussian)."""

    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type, magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, desc, arr):
        shape = arr.shape
        if len(shape) < 2:
            # fall back to uniform for 1-D params routed here
            arr._set_data(jax.random.uniform(next_key(), shape, jnp.float32, -0.07, 0.07)
                          .astype(arr._data.dtype))
            return
        hw_scale = 1.0
        for s in shape[2:]:
            hw_scale *= s
        fan_in, fan_out = shape[1] * hw_scale, shape[0] * hw_scale
        if self.factor_type == "in":
            factor = fan_in
        elif self.factor_type == "out":
            factor = fan_out
        else:
            factor = (fan_in + fan_out) / 2.0
        scale = math.sqrt(self.magnitude / factor)
        if self.rnd_type == "uniform":
            d = jax.random.uniform(next_key(), shape, jnp.float32, -scale, scale)
        else:
            d = jax.random.normal(next_key(), shape) * scale
        arr._set_data(d.astype(arr._data.dtype))


@register
class MSRAPrelu(Xavier):
    """Ref: initializer.py:MSRAPrelu."""

    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register
class Orthogonal(Initializer):
    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, _, arr):
        nout = arr.shape[0]
        nin = int(_np.prod(arr.shape[1:]))
        if self.rand_type == "uniform":
            tmp = jax.random.uniform(next_key(), (nout, nin), jnp.float32, -1, 1)
        else:
            tmp = jax.random.normal(next_key(), (nout, nin))
        u, _, v = jnp.linalg.svd(tmp, full_matrices=False)
        q = u if u.shape == (nout, nin) else v
        arr._set_data((self.scale * q).reshape(arr.shape).astype(arr._data.dtype))


@register
class Bilinear(Initializer):
    """Upsampling deconv kernel init (ref: initializer.py:Bilinear)."""

    def _init_weight(self, _, arr):
        shape = arr.shape
        weight = _np.zeros(int(_np.prod(shape)), dtype=_np.float32)
        f = _np.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(weight.size):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        arr._set_data(jnp.asarray(weight.reshape(shape)).astype(arr._data.dtype))


@register
class LSTMBias(Initializer):
    """Forget-gate bias = 1 (ref: initializer.py:LSTMBias)."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, desc, arr):
        b = _np.zeros(arr.shape, _np.float32)
        num_hidden = arr.shape[0] // 4
        b[num_hidden:2 * num_hidden] = self.forget_bias
        arr._set_data(jnp.asarray(b).astype(arr._data.dtype))

    _init_default = _init_weight


class Mixed:
    """Pattern → initializer mapping (ref: initializer.py:Mixed)."""

    def __init__(self, patterns, initializers):
        self.map = list(zip([re.compile(p) for p in patterns], initializers))

    def __call__(self, name, arr):
        for prog, init in self.map:
            if prog.match(name):
                init(name, arr)
                return
        raise MXNetError("Parameter %s did not match any pattern" % name)


def create(init, **kwargs) -> Initializer:
    if isinstance(init, Initializer):
        return init
    if isinstance(init, str):
        val = init
        if val.startswith("["):  # dumps() format
            name, kw = json.loads(val)
            return _REGISTRY[name](**kw)
        return _REGISTRY[val.lower()](**kwargs)
    raise MXNetError("cannot create initializer from %r" % (init,))
