"""Legacy multi-device executor helpers (ref: python/mxnet/
executor_manager.py — DataParallelExecutorManager behind mx.model
FeedForward).

The TPU build replaces per-device executor groups with ONE GSPMD-sharded
executor (mxtpu/symbol/executor.py binds to a jax Mesh; the batch is
sharded over the 'data' axis and gradient reduction is an implicit XLA
all-reduce). Only ``_split_input_slice`` — the public batch-slicing helper
some reference training scripts import directly — is provided.
"""
from __future__ import annotations

from .base import MXNetError

__all__ = ["_split_input_slice"]


def _split_input_slice(batch_size, work_load_list):
    """Split batch_size into per-worker slices proportional to work_load_list
    (ref: executor_manager.py:_split_input_slice). Raises when the batch is
    too small to give every worker at least one sample, like the reference."""
    total = sum(work_load_list)
    slices = []
    start = 0
    for i, w in enumerate(work_load_list):
        end = (batch_size * sum(work_load_list[:i + 1]) + total - 1) // total
        end = min(end, batch_size)
        if end <= start:
            raise MXNetError("too many slices: batch %d over %d workers"
                             % (batch_size, len(work_load_list)))
        slices.append(slice(start, end))
        start = end
    return slices
