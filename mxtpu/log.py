"""Colored logging helper (ref: python/mxnet/log.py).

``get_logger`` / ``getLogger`` configure a logger with the reference's
level-labelled format (and ANSI colors on TTYs), so training scripts that
set up logging through mx.log port unchanged.
"""
from __future__ import annotations

import logging
import sys

__all__ = ["get_logger", "getLogger",
           "DEBUG", "INFO", "WARNING", "ERROR", "CRITICAL", "NOTSET"]

DEBUG = logging.DEBUG
INFO = logging.INFO
WARNING = logging.WARNING
ERROR = logging.ERROR
CRITICAL = logging.CRITICAL
NOTSET = logging.NOTSET

_COLORS = {logging.WARNING: "\x1b[0;33m", logging.ERROR: "\x1b[0;31m",
           logging.CRITICAL: "\x1b[0;35m", logging.DEBUG: "\x1b[0;34m"}
_LABELS = {logging.DEBUG: "D", logging.INFO: "I", logging.WARNING: "W",
           logging.ERROR: "E", logging.CRITICAL: "C"}


class _Formatter(logging.Formatter):
    """Level-labelled, optionally colored (ref: log.py:_Formatter)."""

    def __init__(self, colored):
        super().__init__(datefmt="%m%d %H:%M:%S")
        self._colored = colored

    def format(self, record):
        label = _LABELS.get(record.levelno, "U")
        if self._colored and record.levelno in _COLORS:
            head = _COLORS[record.levelno] + label + "\x1b[0m"
        else:
            head = label
        self._style._fmt = head + "%(asctime)s %(process)d %(pathname)s:%(lineno)d] %(message)s"
        return super().format(record)


def get_logger(name=None, filename=None, filemode=None, level=WARNING):
    """Configured logger (ref: log.py:getLogger semantics: idempotent per
    name; file handler when filename given, else stderr with colors on
    TTYs)."""
    logger = logging.getLogger(name)
    if name is None:
        # reference behavior (log.py:80): never install handlers on or
        # re-level the ROOT logger — that would reformat every third-party
        # library's records and double-print named loggers via propagation
        return logger
    if getattr(logger, "_mxtpu_init", False):
        return logger
    if filename:
        handler = logging.FileHandler(filename, filemode or "a")
        handler.setFormatter(_Formatter(colored=False))
    else:
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(_Formatter(colored=sys.stderr.isatty()))
    logger.addHandler(handler)
    logger.setLevel(level)
    logger._mxtpu_init = True
    return logger


getLogger = get_logger  # reference spelling
