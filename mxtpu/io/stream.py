"""Device-resident streaming input pipeline (ISSUE 9, ROADMAP item 4).

The multiprocess DataLoader (PR 3) keeps the decode work off the trainer
thread, but its batches still arrive as HOST arrays that the training step
uploads synchronously at the jit boundary — the ``data.wait`` telemetry
span measures the devices sitting idle while the host finishes decoding
AND transferring. With multi-chip training (PR 7) shrinking per-step
compute near-linearly, that host time grows relative to the step. This
module closes the gap with the input-side twin of the cross-replica
update sharding:

* :func:`shard_keys` — a deterministic, seedable, epoch-reshuffled,
  remainder-balanced partition of a RecordIO index across hosts/replicas
  (no record dropped or duplicated, shard sizes differ by at most one).
* :class:`ShardedRecordReader` — streams decoded+batchified batches from
  ONE shard of an ``MXIndexedRecordIO`` file on a small THREAD pool
  (``MXTPU_STREAM_THREADS``) instead of the fork-heavy process pool:
  record-backed datasets decode in C (numpy/cv2 release the GIL), so
  threads overlap fine and share one pread-positioned file handle
  (``recordio.MXIndexedRecordIO.pread_idx``) with no seek races and no
  spawn/pickling tax. Worker death rides PR 3's recovery discipline:
  dead workers restart under the ``MXTPU_DL_WORKER_RESTARTS`` budget with
  their in-flight batches re-enqueued; ``worker_death`` (reader pool)
  and ``prefetch_death`` (prefetch producer) fault injection drive the
  paths deterministically in tier-1.
* :class:`DevicePrefetcher` — the double-buffered prefetch-to-device
  stage: a producer thread issues the (async) ``jax.device_put`` of batch
  N+1 while the consumer computes on batch N, keeping up to
  ``MXTPU_PREFETCH_DEPTH`` batches in flight. When a target ``Sharding``
  is supplied (e.g. the mesh Trainer's batch layout via
  ``Trainer.batch_sharding``) the put lands each per-replica slice
  directly on its device — no host-side gather, and the training step's
  input is already laid out the way ``Trainer.shard_batch`` would have
  placed it. ``data.wait`` then measures only TRUE starvation
  (buffer-empty), with ``data.h2d`` timing the transfer issue,
  ``data.prefetch_depth`` publishing the configured depth and
  ``data.starved`` counting the empty-buffer events.
* :class:`StreamRecordIter` — the two pieces composed behind the classic
  ``DataIter`` surface so the module path rides the same pipeline the
  gluon ``DataLoader(prefetch_to_device=...)`` path does.

Everything here is host-side control flow — no jit, no policy levers; the
env knobs are runtime-shape only and documented in docs/env_vars.md
(guidance: docs/data_pipeline.md).
"""
from __future__ import annotations

import collections
import os
import threading

import numpy as np

from .. import telemetry
from ..base import MXNetError
from .io import DataBatch, DataDesc, DataIter

__all__ = ["shard_keys", "ShardedRecordReader", "DevicePrefetcher",
           "StreamRecordIter", "prefetch_depth", "stream_threads"]


def prefetch_depth(default=None):
    """``MXTPU_PREFETCH_DEPTH``: batches the prefetcher keeps in flight
    ahead of the consumer (default 2 — classic double buffering: one on
    device computing, one in transfer). Clamped to >= 1 on BOTH paths: a
    depth of 0 would make the producer's backpressure check permanently
    true — it never produces, never dies, and the consumer hangs."""
    if default is not None:
        return max(1, int(default))
    return max(1, int(os.environ.get("MXTPU_PREFETCH_DEPTH", "2")))


def stream_threads(default=None):
    """``MXTPU_STREAM_THREADS``: decode/batchify thread-pool width of
    :class:`ShardedRecordReader` (default 2; records decode in
    GIL-releasing C, so a small pool overlaps read+decode with the
    consumer without the process pool's spawn/pickling tax). An explicit
    ``0`` selects the inline path: decode on the CONSUMER thread, fully
    synchronous — the A/B baseline the ``bench.py input_pipeline`` config
    measures overlap against (the env spelling honors 0 the same way)."""
    if default is not None:
        return max(0, int(default))
    return max(0, int(os.environ.get("MXTPU_STREAM_THREADS", "2")))


# ------------------------------------------------------------ index sharding
def shard_keys(keys, num_shards=1, shard_index=0, epoch=0, seed=0,
               shuffle=True):
    """Deterministic per-replica slice of a record index.

    The permutation is a pure function of ``(seed, epoch)`` — every
    host/replica computes the SAME epoch order from the shared seed and
    takes its own contiguous slice, so shards are disjoint and their
    union is exactly ``keys`` (nothing dropped, nothing duplicated).
    Remainder balancing: when ``num_shards`` does not divide ``len(keys)``
    the first ``len(keys) % num_shards`` shards carry one extra record —
    sizes differ by at most one, and every record is served each epoch
    (the alternative — padding or dropping the tail — silently biases
    small datasets). A new ``epoch`` reshuffles; ``shuffle=False`` keeps
    index order (the slice boundaries still balance the remainder).
    """
    n = len(keys)
    if num_shards < 1:
        raise MXNetError("num_shards must be >= 1, got %d" % num_shards)
    if not 0 <= shard_index < num_shards:
        raise MXNetError("shard_index %d outside [0, %d)"
                         % (shard_index, num_shards))
    if shuffle:
        # seed sequence, not seed+epoch arithmetic: distinct (seed, epoch)
        # pairs must never collide into one permutation
        order = np.random.RandomState([int(seed), int(epoch)]).permutation(n)
    else:
        order = np.arange(n)
    base, rem = divmod(n, num_shards)
    lo = shard_index * base + min(shard_index, rem)
    hi = lo + base + (1 if shard_index < rem else 0)
    return [keys[i] for i in order[lo:hi]]


def _default_batchify(samples):
    """Numpy-only stacking (the worker-pool batchify contract): arrays
    stack along a new batch dim, tuples transpose-and-recurse, anything
    else stays a list (raw record bytes etc.).

    Deliberately NOT shared with the gluon batchifies (this module sits
    below gluon in the layering): ``gluon/data/_mp_worker.
    default_mp_batchify_fn`` must REJECT device arrays (spawn-worker
    contract) and ``gluon/data/dataloader._prefetch_batchify_fn`` must
    stack them and return lists (the reference DataLoader API); this one
    keeps tuple-ness so ``StreamRecordIter._wrap`` can split
    ``(data, label)`` and passes raw bytes through. A framing change to
    one should be weighed against the other two."""
    first = samples[0]
    if isinstance(first, tuple):
        return tuple(_default_batchify(list(col)) for col in zip(*samples))
    if isinstance(first, (np.ndarray, np.generic, float, int)):
        return np.asarray(samples)
    return list(samples)


class _WorkerDied(Exception):
    """Internal marker: the injected silent-death path (a real thread
    cannot be SIGKILLed — death is modeled as exiting without publishing,
    which is what the OOM-killed process worker looks like from the
    consumer's side)."""


class ShardedRecordReader:
    """Streaming batches from one deterministic shard of an indexed
    RecordIO file.

    Each ``__iter__`` pass is one epoch: the shard's keys for the CURRENT
    epoch (see :func:`shard_keys`) are split into ``batch_size`` groups,
    read with positioned preads off one shared handle, decoded and
    batchified on the thread pool, and delivered IN ORDER — so two runs
    with the same seed produce identical per-replica batch streams, which
    is what makes multi-host training resumable and debuggable. The epoch
    counter advances on exhaustion of the epoch iterator (a mid-epoch
    abandon does not — the next pass replays the same epoch order).
    Caveat: under a :class:`DevicePrefetcher`, exhaustion is driven by
    the PRODUCER thread's read-ahead, so an abandon within ~depth batches
    of the epoch end may find the epoch already advanced —
    :class:`StreamRecordIter` compensates (consumer-driven replay via
    ``set_epoch``); raw reader+prefetcher compositions should do the
    same.

    ``last_batch``: ``'keep'`` (default) emits the short tail batch;
    ``'discard'`` drops it (mesh consumers that need the batch dim to
    divide the data axis set ``'discard'`` or pick dividing batch sizes).
    """

    def __init__(self, rec_path, idx_path=None, batch_size=1, decode_fn=None,
                 batchify_fn=None, num_shards=1, shard_index=0, seed=0,
                 shuffle=True, num_threads=None, last_batch="keep"):
        from ..recordio import MXIndexedRecordIO
        if idx_path is None:
            root = rec_path[:rec_path.rfind(".")] if "." in \
                os.path.basename(rec_path) else rec_path
            idx_path = root + ".idx"
        if last_batch not in ("keep", "discard"):
            raise MXNetError("last_batch must be 'keep' or 'discard', got %r"
                             % (last_batch,))
        self._record = MXIndexedRecordIO(idx_path, rec_path, "r")
        if not self._record.keys:
            raise MXNetError("empty or missing index: %s" % idx_path)
        self.batch_size = int(batch_size)
        self.decode_fn = decode_fn
        self.batchify_fn = batchify_fn or _default_batchify
        self.num_shards = num_shards
        self.shard_index = shard_index
        self.seed = seed
        self.shuffle = shuffle
        self.last_batch = last_batch
        self.num_threads = stream_threads(num_threads)
        self._epoch = 0
        self._closed = False

    # epoch control -------------------------------------------------------
    @property
    def epoch(self):
        return self._epoch

    def set_epoch(self, epoch):
        """Pin the epoch (resume path: a restored loop re-seeds the stream
        at the checkpointed epoch and replays the identical order)."""
        self._epoch = int(epoch)

    def shard_len(self, epoch=None):
        e = self._epoch if epoch is None else epoch
        return len(shard_keys(self._record.keys, self.num_shards,
                              self.shard_index, e, self.seed, self.shuffle))

    def __len__(self):
        n = self.shard_len()
        if self.last_batch == "discard":
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size

    # one epoch -----------------------------------------------------------
    def _epoch_batches(self):
        keys = shard_keys(self._record.keys, self.num_shards,
                          self.shard_index, self._epoch, self.seed,
                          self.shuffle)
        batches = [keys[i:i + self.batch_size]
                   for i in range(0, len(keys), self.batch_size)]
        if batches and self.last_batch == "discard" and \
                len(batches[-1]) < self.batch_size:
            batches.pop()
        return batches

    def _load(self, key_batch):
        samples = []
        for k in key_batch:
            raw = self._record.pread_idx(k)
            samples.append(self.decode_fn(raw) if self.decode_fn else raw)
        return self.batchify_fn(samples)

    def __iter__(self):
        if self._closed:
            raise MXNetError("ShardedRecordReader is closed")
        batches = self._epoch_batches()
        if not batches:
            self._epoch += 1
            return
        if self.num_threads == 0:
            # inline synchronous path: decode on the consumer thread (the
            # overlap A/B baseline; also the zero-thread debug spelling)
            for kb in batches:
                yield self._load(kb)
            self._epoch += 1
            return
        yield from self._iter_pool(batches)

    def _iter_pool(self, batches):
        """Thread pool with ordered delivery + PR-3 worker-death recovery.

        Death is detected (a worker gone without publishing its batch),
        not announced: the consumer's bounded condition-wait rechecks pool
        liveness, restarts dead workers under the restart budget and
        re-enqueues their orphaned batch indices. Dataset/decode
        exceptions are NOT deaths — they travel back as results and
        re-raise at the consumer with the batch index."""
        from ..resilience import inject
        lock = threading.Lock()
        ready = threading.Condition(lock)
        results = {}
        pending = collections.deque(range(len(batches)))
        # in-flight work is keyed by a UNIQUE per-worker token, never by
        # threading.get_ident(): pthread ids recycle the moment a worker
        # exits (observed on a 1-core host — a sibling worker first
        # scheduled after the victim's exit carried the SAME ident and
        # clobbered the orphan record, losing the batch forever)
        taken = {}            # worker token -> batch index being processed
        workers = {}          # worker token -> Thread
        stop = threading.Event()
        state = {"next": 0, "restarts": 0, "token": 0}
        bound = max(2 * self.num_threads, 2)
        max_restarts = int(os.environ.get("MXTPU_DL_WORKER_RESTARTS", "3"))

        def worker(token):
            while not stop.is_set():
                with ready:
                    while not pending and not stop.is_set():
                        ready.wait(0.1)
                    if stop.is_set():
                        return
                    i = pending.popleft()
                    # bounded prefetch past the consumer; throttling on
                    # distance-from-consumer can never block the batch the
                    # consumer needs next
                    while i > state["next"] + bound and not stop.is_set():
                        ready.wait(0.1)
                    if stop.is_set():
                        return
                    taken[token] = i
                try:
                    if inject("worker_death", i):
                        # silent death: exit WITHOUT publishing batch i —
                        # the consumer's liveness recheck must find it
                        raise _WorkerDied()
                    out = self._load(batches[i])
                except _WorkerDied:
                    with ready:
                        ready.notify_all()  # wake the consumer promptly
                    return
                except Exception as e:  # noqa: BLE001 — delivered, not lost
                    out = e
                with ready:
                    taken.pop(token, None)
                    results[i] = out
                    ready.notify_all()

        def spawn(n):
            for _ in range(n):
                token = state["token"]
                state["token"] += 1
                t = threading.Thread(target=worker, args=(token,),
                                     daemon=True, name="mxtpu-stream-reader")
                workers[token] = t
                t.start()

        spawn(self.num_threads)
        try:
            for i in range(len(batches)):
                with ready:
                    while i not in results:
                        dead = [tok for tok, t in workers.items()
                                if not t.is_alive()]
                        if dead:
                            # PR-3 discipline: ONE restart event per
                            # detection sweep, budgeted; orphaned batches
                            # re-enqueue (the death consumed no result)
                            state["restarts"] += 1
                            telemetry.inc("stream.worker_restarts")
                            if state["restarts"] > max_restarts:
                                raise RuntimeError(
                                    "stream reader worker(s) died while "
                                    "waiting for batch %d/%d; giving up "
                                    "after %d restart(s) "
                                    "(MXTPU_DL_WORKER_RESTARTS=%d)"
                                    % (i, len(batches),
                                       state["restarts"] - 1, max_restarts))
                            for tok in dead:
                                workers.pop(tok)
                                ix = taken.pop(tok, None)
                                if ix is not None and ix not in results:
                                    pending.appendleft(ix)
                            spawn(self.num_threads - len(workers))
                            ready.notify_all()
                            continue
                        ready.wait(0.1)
                    out = results.pop(i)
                    state["next"] = i + 1
                    ready.notify_all()
                if isinstance(out, Exception):
                    raise RuntimeError(
                        "stream reader failed at batch %d" % i) from out
                yield out
            self._epoch += 1  # full consumption advances the shuffle epoch
        finally:
            stop.set()
            with ready:
                ready.notify_all()
            for t in workers.values():
                t.join(timeout=5.0)

    def close(self):
        if not self._closed:
            self._closed = True
            self._record.close()

    def __del__(self):  # pragma: no cover - interpreter-exit timing
        try:
            self.close()
        except Exception:  # noqa: BLE001
            pass


# -------------------------------------------------------- prefetch-to-device
def _resolve_sharding(spec):
    """``prefetch_to_device=`` spellings -> a jax Sharding or None.

    ``True``/``None`` = default device placement; a ``jax.sharding.
    Sharding`` is used as-is; a gluon ``Trainer`` contributes its
    ``batch_sharding`` (None without a mesh — loops can pass the trainer
    unconditionally, mirroring ``shard_batch``'s identity contract)."""
    if spec is None or spec is True or spec is False:
        return None
    sb = getattr(spec, "batch_sharding", None)
    if sb is not None or hasattr(spec, "_mesh"):
        return sb
    return spec


class DevicePrefetcher:
    """Double-buffered prefetch-to-device over any batch iterator.

    A producer thread pulls host batches and issues ``jax.device_put``
    onto ``sharding`` (async under PJRT — the transfer overlaps the
    consumer's compute on the previous batch), keeping at most ``depth``
    batches buffered. Batch leaves handled: numpy arrays (uploaded),
    ``NDArray`` (re-placed only when a sharding is given — already
    device-resident otherwise), ``DataBatch``/list/tuple/dict containers
    (mapped), scalars/None (passthrough).

    With a ``NamedSharding`` target whose dim 0 divides the batch, each
    per-replica slice lands directly on its device — the mesh path never
    gathers on the host. A non-dividing tail batch degrades to default
    placement (documented in docs/data_pipeline.md) rather than failing
    the epoch.

    Telemetry: ``data.prefetch_depth`` gauge (configured depth),
    ``data.h2d`` span per transfer issue (producer thread),
    ``data.wait`` span = time the CONSUMER blocked on an empty buffer
    (true starvation only), ``data.starved`` counter per such event.

    Failure discipline (PR 3): a source/transfer exception is delivered
    at the consumer, not lost; an injected silent producer death
    (``prefetch_death`` fault kind — its own kind, so composed pipelines
    stay deterministic vs the reader/mp pools' ``worker_death``) is
    detected by the consumer's bounded
    wait and the producer restarts under ``MXTPU_DL_WORKER_RESTARTS``,
    resuming the SAME source iterator (nothing skipped: death is injected
    between batches). ``close()`` is bounded: it drains the buffer so a
    blocked producer wakes, joins with a timeout, and closes a generator
    source so its ``finally`` cleanup (worker pools, shm segments) runs.

    ``to_device=False`` makes this a pure HOST double buffer (no
    ``device_put``, no ``<site>.h2d`` span) — the decode-ahead sub
    stages of a multi-iterator ``PrefetchingIter`` use it so the ONE
    H2D transfer stays with the outer, sharding-aware stage.
    """

    def __init__(self, source, depth=None, sharding=None, site="data",
                 to_device=True):
        self._source = iter(source)
        self._depth = prefetch_depth(depth)
        self._sharding = _resolve_sharding(sharding)
        self._put = bool(to_device)
        self._site = site
        self._buf = collections.deque()
        self._cv = threading.Condition()
        self._finished = False   # producer published end-of-stream
        self._stopped = False    # consumer asked for shutdown
        self._error = None
        self._restarts = 0
        self._thread = None
        telemetry.gauge("%s.prefetch_depth" % site, self._depth)
        self._start()

    def _start(self):
        self._thread = threading.Thread(target=self._produce, daemon=True,
                                        name="mxtpu-prefetch")
        self._thread.start()

    # producer ------------------------------------------------------------
    def _produce(self):
        from ..resilience import inject
        try:
            while True:
                with self._cv:
                    while len(self._buf) >= self._depth and \
                            not self._stopped:
                        self._cv.wait(0.1)
                    if self._stopped:
                        return
                # own fault kind, NOT worker_death: the reader pool and
                # the mp DataLoader check worker_death@batch-index, and
                # this counter-indexed check would race them for the same
                # (kind, index) in composed pipelines — which stage dies
                # would depend on thread scheduling, breaking inject()'s
                # determinism contract
                if inject("prefetch_death"):
                    return  # silent: no sentinel — the consumer detects
                try:
                    batch = next(self._source)
                except StopIteration:
                    break
                if self._put:
                    # the transfer gets its own trace on THIS (producer)
                    # thread; its context rides the buffer entry so the
                    # CONSUMER pends it — the training step that eats
                    # this batch links the h2d that produced it
                    with telemetry.span("%s.h2d" % self._site,
                                        new_trace=True) as sp:
                        item = self._to_device(batch)
                    h2d_ctx = sp.ctx
                else:
                    item = batch  # host-only stage: no device placement
                    h2d_ctx = None
                with self._cv:
                    if self._stopped:
                        return
                    self._buf.append((item, h2d_ctx))
                    self._cv.notify_all()
            with self._cv:
                self._finished = True
                self._cv.notify_all()
        except BaseException as e:  # noqa: BLE001 — delivered to consumer
            with self._cv:
                self._error = e
                self._finished = True
                self._cv.notify_all()

    def _to_device(self, obj):
        import jax

        from ..ndarray import NDArray
        sh = self._sharding

        def put(x, leaf_sh):
            return NDArray(jax.device_put(x, leaf_sh) if leaf_sh is not None
                           else jax.device_put(x))

        def rec(x):
            if isinstance(x, DataBatch):
                out = DataBatch.__new__(DataBatch)
                out.__dict__.update(x.__dict__)
                out.data = rec(x.data)
                out.label = rec(x.label)
                return out
            if isinstance(x, (list, tuple)):
                mapped = [rec(v) for v in x]
                return tuple(mapped) if isinstance(x, tuple) else mapped
            if isinstance(x, dict):
                return {k: rec(v) for k, v in x.items()}
            if isinstance(x, NDArray):
                if sh is None:
                    return x  # already device-resident
                return NDArray(jax.device_put(x._data, self._leaf(x._data)))
            if isinstance(x, (np.ndarray, np.generic)):
                return put(np.asarray(x), self._leaf(x))
            return x

        return rec(obj)

    def _leaf(self, x):
        """Per-leaf sharding: the batch-axis NamedSharding when dim 0
        divides it, default placement for the remainder tail (degradation
        matrix row in docs/data_pipeline.md)."""
        sh = self._sharding
        if sh is None:
            return None
        shape = getattr(x, "shape", ())
        mesh = getattr(sh, "mesh", None)
        spec = getattr(sh, "spec", None)
        if mesh is not None and spec is not None and spec:
            axis = spec[0]
            if axis is not None:
                n = mesh.shape[axis] if not isinstance(axis, tuple) else \
                    int(np.prod([mesh.shape[a] for a in axis]))
                if not shape or shape[0] % n:
                    return None
        return sh

    # consumer ------------------------------------------------------------
    def __iter__(self):
        return self

    def __next__(self):
        max_restarts = int(os.environ.get("MXTPU_DL_WORKER_RESTARTS", "3"))
        with self._cv:
            starved = not self._buf and not self._finished and \
                not self._stopped
            if starved:
                telemetry.inc("%s.starved" % self._site)
            with telemetry.span("%s.wait" % self._site,
                                new_trace=True) as wait_sp:
                while not self._buf and not self._finished and \
                        not self._stopped:
                    if not self._thread.is_alive():
                        # producer died silently (injected
                        # prefetch_death):
                        # restart against the same source iterator under
                        # the PR-3 budget
                        self._restarts += 1
                        telemetry.inc("%s.prefetch_restarts" % self._site)
                        if self._restarts > max_restarts:
                            raise RuntimeError(
                                "prefetch worker died; giving up after %d "
                                "restart(s) (MXTPU_DL_WORKER_RESTARTS=%d)"
                                % (self._restarts - 1, max_restarts))
                        self._start()
                    self._cv.wait(0.1)
            if not self._buf:
                # a concurrent close() ends the stream cleanly — it must
                # never read as a worker death (spurious restarts + a
                # fake 'worker died' RuntimeError for a normal shutdown)
                if self._stopped:
                    raise StopIteration
                # deliver buffered batches BEFORE a trailing error: the
                # consumer sees every good batch, then the failure
                if self._error is not None:
                    err, self._error = self._error, None
                    raise err
                raise StopIteration
            item, h2d_ctx = self._buf.popleft()
            self._cv.notify_all()
            # hand-over: the NEXT trainer.step trace adopts these as
            # cross-thread causal links (telemetry.link_pending) — the
            # step that consumes this batch owns its wait + transfer
            telemetry.pend_link("%s.h2d" % self._site, h2d_ctx)
            telemetry.pend_link("%s.wait" % self._site, wait_sp.ctx)
            return item

    def next(self):
        return self.__next__()

    def close(self, timeout=5.0, reraise=False):
        """Bounded shutdown: wake a blocked producer, join with
        ``timeout``, close a generator source so its cleanup runs. With
        ``reraise=True`` a pending producer error raises here instead of
        being dropped (the PrefetchingIter.reset contract). A join that
        TIMES OUT is not silent: the producer is still inside the source
        iterator, so a caller about to reset/re-consume that source
        (PrefetchingIter.reset) would race the zombie — ``reraise=True``
        refuses with a RuntimeError, plain close warns."""
        with self._cv:
            self._stopped = True
            self._buf.clear()
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            if self._thread.is_alive():
                msg = ("prefetch worker did not exit within %.1fs — it is "
                       "still blocked inside the source iterator; the "
                       "source is NOT safe to reset or re-consume yet"
                       % timeout)
                if reraise:
                    raise RuntimeError(msg)
                import warnings
                warnings.warn(msg)
                return
        src_close = getattr(self._source, "close", None)
        if src_close is not None:
            try:
                src_close()
            except Exception:  # noqa: BLE001 — teardown must not mask
                pass
        if reraise and self._error is not None:
            err, self._error = self._error, None
            raise err

    def __del__(self):  # pragma: no cover - interpreter-exit timing
        try:
            self.close(timeout=0.5)
        except Exception:  # noqa: BLE001
            pass


# --------------------------------------------------------------- DataIter
class StreamRecordIter(DataIter):
    """``DataIter`` over the streaming pipeline: sharded positioned reads
    -> thread-pool decode/batchify -> double-buffered prefetch-to-device.

    ``decode_fn(raw) -> sample`` should return a numpy array or a
    ``(data, label)`` tuple of numpy arrays; batches then arrive as
    device-resident ``DataBatch``\\ es (on ``sharding`` when given — pass
    the mesh ``Trainer`` itself to land per-replica slices directly), so
    both the module path and hand-rolled loops ride the same overlap the
    gluon ``DataLoader(prefetch_to_device=...)`` path gets.

    ``reset()`` closes the in-flight prefetcher (bounded join) and starts
    the next epoch — which reshuffles, per :func:`shard_keys`, only if
    the previous epoch was fully consumed BY THE CONSUMER: the
    prefetcher's read-ahead may exhaust the reader generator a few
    batches early (advancing its epoch producer-side), so reset()
    restores the reader epoch whenever this iterator never delivered the
    epoch's final batch — the replay contract is consumer-driven
    regardless of depth.

    ``prefetch_to_device=False`` disables the device stage entirely:
    batches arrive as HOST numpy (inline pull, no producer thread) —
    for host-side augmentation or keeping device memory free."""

    def __init__(self, rec_path, idx_path=None, batch_size=1, decode_fn=None,
                 batchify_fn=None, num_shards=1, shard_index=0, seed=0,
                 shuffle=True, num_threads=None, last_batch="keep",
                 prefetch_to_device=True, sharding=None, depth=None,
                 data_name="data", label_name="softmax_label"):
        super().__init__(batch_size)
        if decode_fn is None and batchify_fn is None:
            # without either, batches are raw record BYTES — no
            # shape/dtype to form a DataBatch/DataDesc from; fail here
            # with the fix named instead of an AttributeError from the
            # producer thread at the first next()
            raise MXNetError(
                "StreamRecordIter needs a decode_fn(raw_bytes) -> numpy "
                "sample (or (data, label) tuple), or a batchify_fn that "
                "turns raw records into arrays — e.g. decode via "
                "recordio.unpack/unpack_img (docs/data_pipeline.md). For "
                "raw-bytes streaming use ShardedRecordReader directly.")
        self._reader = ShardedRecordReader(
            rec_path, idx_path, batch_size=batch_size, decode_fn=decode_fn,
            batchify_fn=batchify_fn, num_shards=num_shards,
            shard_index=shard_index, seed=seed, shuffle=shuffle,
            num_threads=num_threads, last_batch=last_batch)
        self._prefetch = prefetch_to_device not in (None, False)
        self._sharding = sharding if self._prefetch else None
        self._depth = depth
        self._data_name = data_name
        self._label_name = label_name
        self._prefetcher = None
        self._pending = None
        self._descs = None
        self._start()

    def _start(self):
        self._pending = None
        self._exhausted = False
        self._delivered = 0
        self._epoch0 = self._reader.epoch
        self._len0 = len(self._reader)
        src = self._wrap(iter(self._reader))
        self._prefetcher = DevicePrefetcher(
            src, depth=self._depth, sharding=self._sharding) \
            if self._prefetch else src

    def _wrap(self, it):
        try:
            for batch in it:
                if isinstance(batch, tuple) and len(batch) == 2:
                    data, label = batch
                else:
                    data, label = batch, None
                n = data[0].shape[0] if isinstance(data, (list, tuple)) \
                    else data.shape[0]
                yield DataBatch(data=data, label=label,
                                pad=self.batch_size - n)
        finally:
            # a GeneratorExit here (prefetcher close) must reach the
            # reader generator's finally too, or its pool threads outlive
            # the epoch
            close = getattr(it, "close", None)
            if close is not None:
                close()

    def _fill(self):
        if self._pending is None:
            try:
                self._pending = next(self._prefetcher)
            except StopIteration:
                self._exhausted = True
                return False
            if self._descs is None:
                b = self._pending
                self._descs = (
                    [DataDesc("%s%s" % (self._data_name,
                                        "" if i == 0 else "_%d" % i),
                              d.shape, d.dtype)
                     for i, d in enumerate(b.data)],
                    [DataDesc("%s%s" % (self._label_name,
                                        "" if i == 0 else "_%d" % i),
                              l.shape, l.dtype)
                     for i, l in enumerate(b.label or [])])
        return True

    @property
    def provide_data(self):
        self._fill()
        return self._descs[0] if self._descs else None

    @property
    def provide_label(self):
        self._fill()
        return self._descs[1] if self._descs else None

    def iter_next(self):
        return self._fill()

    def next(self):
        if not self._fill():
            raise StopIteration
        batch, self._pending = self._pending, None
        self._delivered += 1
        return batch

    def _close_pipe(self, reraise=False):
        if isinstance(self._prefetcher, DevicePrefetcher):
            self._prefetcher.close(reraise=reraise)
        elif self._prefetcher is not None:
            self._prefetcher.close()  # host generator: runs _wrap's finally

    def reset(self):
        self._close_pipe(reraise=True)
        # full consumption is judged by DELIVERED batches, not by whether
        # an extra next() observed StopIteration: a step-counted loop
        # (`for _ in range(len(it)): it.next()`) consumed the whole epoch
        # and must progress the shuffle, while a genuine mid-epoch
        # abandon replays — and neither the prefetcher's read-ahead nor
        # the host generator's suspended epoch increment can be trusted
        # to have left the reader's counter right for either case
        if self._exhausted or self._delivered >= self._len0:
            if self._reader.epoch == self._epoch0:
                self._reader.set_epoch(self._epoch0 + 1)
        else:
            self._reader.set_epoch(self._epoch0)
        self._start()

    def close(self):
        self._close_pipe()
        self._reader.close()

    def __del__(self):  # pragma: no cover - interpreter-exit timing
        try:
            self.close()
        except Exception:  # noqa: BLE001
            pass
