"""Data iterators (ref: python/mxnet/io/io.py).

TPU-native notes: batches are host numpy until the training step consumes
them — device transfer happens once per batch at the jit boundary. The
reference's PrefetcherIter double-buffering maps to the async
``jax.device_put`` pipeline in ``mxtpu/io/stream.py`` (DevicePrefetcher):
``PrefetchingIter`` here delegates to it, and ``StreamRecordIter`` is the
sharded streaming RecordIO spelling of the same overlap (ISSUE 9,
docs/data_pipeline.md).
"""
from __future__ import annotations

from collections import namedtuple

import numpy as np

from ..base import MXNetError
from ..ndarray import NDArray, array

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "ResizeIter",
           "MNISTIter", "ImageRecordIter",
           "PrefetchingIter", "CSVIter", "LibSVMIter"]


class DataDesc(namedtuple("DataDesc", ["name", "shape", "dtype", "layout"])):
    """Data description: name/shape/dtype/layout (ref: io.py:DataDesc)."""

    def __new__(cls, name, shape, dtype=np.float32, layout="NCHW"):
        return super().__new__(cls, name, tuple(shape), dtype, layout)

    @staticmethod
    def get_batch_axis(layout):
        return 0 if layout is None else layout.find("N")


class DataBatch:
    """One mini-batch (ref: io.py:DataBatch)."""

    def __init__(self, data, label=None, pad=0, index=None,
                 bucket_key=None, provide_data=None, provide_label=None):
        if data is not None and not isinstance(data, (list, tuple)):
            data = [data]
        if label is not None and not isinstance(label, (list, tuple)):
            label = [label]
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label


class DataIter:
    """Iterator base (ref: io.py:DataIter)."""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self):
        if self.iter_next():
            return DataBatch(data=self.getdata(), label=self.getlabel(),
                             pad=self.getpad(), index=self.getindex())
        raise StopIteration

    def __next__(self):
        return self.next()

    def iter_next(self):
        raise NotImplementedError

    def getdata(self):
        raise NotImplementedError

    def getlabel(self):
        raise NotImplementedError

    def getindex(self):
        return None

    def getpad(self):
        raise NotImplementedError


def _init_data(data, allow_empty, default_name):
    """Normalize input data to list of (name, np.ndarray) (ref: io.py:_init_data)."""
    if data is None:
        if not allow_empty:
            raise ValueError("data cannot be None")
        return []
    if isinstance(data, (np.ndarray, NDArray)):
        data = [data]
    if isinstance(data, (list, tuple)):
        if not allow_empty and len(data) == 0:
            raise ValueError("data cannot be empty")
        data = {(default_name if len(data) == 1 else "_%d_%s" %
                 (i, default_name)): d for i, d in enumerate(data)}
    if not isinstance(data, dict):
        raise TypeError("Input must be NDArray, numpy.ndarray, list or dict")
    out = []
    for k, v in data.items():
        v = v.asnumpy() if isinstance(v, NDArray) else np.asarray(v)
        out.append((k, v))
    return out


class NDArrayIter(DataIter):
    """Iterate over in-memory arrays (ref: io.py:NDArrayIter) with pad /
    discard / roll_over last-batch handling."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data",
                 label_name="softmax_label"):
        super().__init__(batch_size)
        self.data = _init_data(data, False, data_name)
        self.label = _init_data(label, True, label_name)
        self.num_data = self.data[0][1].shape[0]
        for k, v in self.data + self.label:
            if v.shape[0] != self.num_data:
                raise MXNetError("all data must have the same length")
        self.shuffle = shuffle
        self.last_batch_handle = last_batch_handle
        if last_batch_handle == "discard":
            self.num_batches = self.num_data // batch_size
        else:
            self.num_batches = (self.num_data + batch_size - 1) // batch_size
        self._order = np.arange(self.num_data)
        self._leftover = np.array([], dtype=np.int64)
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.data]

    @property
    def provide_label(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.label]

    def reset(self):
        base = np.arange(self.num_data)
        if self.shuffle:
            np.random.shuffle(base)
        if self.last_batch_handle == "roll_over":
            # reference semantics: the incomplete tail batch is NOT
            # emitted this epoch — it rolls over and leads the next
            # epoch's stream (io.py NDArrayIter roll_over; what
            # BucketSentenceIter round_batch relies on). The tail only
            # carries if the previous epoch was fully consumed: a
            # mid-epoch reset abandons its PLANNED tail rather than
            # rolling samples from an epoch that never finished
            # (ADVICE r4; mirrors the reference caching the tail only
            # when iteration actually reached it).
            if not getattr(self, "_exhausted", False):
                self._leftover = np.array([], dtype=np.int64)
            eff = np.concatenate([self._leftover, base])
            n_full = len(eff) // self.batch_size
            self.num_batches = n_full
            self._leftover = eff[n_full * self.batch_size:]
            self._order = eff[:n_full * self.batch_size]
        else:
            self._order = base
        self._cursor = -1
        self._exhausted = False

    def iter_next(self):
        self._cursor += 1
        if self._cursor >= self.num_batches - 1:
            # serving the FINAL batch counts as full consumption: consumers
            # that read exactly num_batches batches (for _ in range(n))
            # never make the extra failing call, and the roll_over tail
            # must still carry for them
            self._exhausted = True
        return self._cursor < self.num_batches

    def _slice(self, arrays):
        start = self._cursor * self.batch_size
        end = start + self.batch_size
        out = []
        for _, v in arrays:
            idx = self._order[start:end]
            chunk = v[idx]
            if chunk.shape[0] < self.batch_size:
                # pad policy (roll_over never reaches here: its epoch
                # holds only full batches). Fill by WRAPPING from the
                # epoch's start — the reference pads with real leading
                # samples, not zeros; DataBatch.pad tells consumers how
                # many trailing rows to ignore either way
                wrap = self._order[:self.batch_size - chunk.shape[0]]
                chunk = np.concatenate([chunk, v[wrap]], axis=0)
            out.append(array(chunk))
        return out

    def getdata(self):
        return self._slice(self.data)

    def getlabel(self):
        return self._slice(self.label)

    def getpad(self):
        """Trailing rows of this batch that are filler, not real samples.

        Intentional divergence (ADVICE r4): under roll_over the reference
        reports a nonzero pad (-cursor) on the first batch after an epoch
        boundary even though that batch holds only real samples (cached
        tail + new ones). Here roll_over epochs contain full batches of
        real samples exclusively, so pad is honestly 0 — consumers that
        mask `batch[:-pad]` drop nothing real."""
        start = self._cursor * self.batch_size
        remaining = self.num_data - start
        if self.last_batch_handle == "pad" and remaining < self.batch_size:
            return self.batch_size - remaining
        return 0

    def getindex(self):
        start = self._cursor * self.batch_size
        return self._order[start:start + self.batch_size]


class ResizeIter(DataIter):
    """Resize (truncate/loop) another iterator to a fixed number of batches
    per epoch (ref: io.py:ResizeIter)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__(data_iter.batch_size)
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.provide_data = data_iter.provide_data
        self.provide_label = data_iter.provide_label

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        return self.cur < self.size

    def next(self):
        if not self.iter_next():
            raise StopIteration
        self.cur += 1
        try:
            return self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            return self.data_iter.next()


class PrefetchingIter(DataIter):
    """Double-buffering over one or more iterators (ref:
    io.py:PrefetchingIter ~ the C++ PrefetcherIter, src/io/
    iter_prefetcher.h), delegating to :class:`mxtpu.io.stream.
    DevicePrefetcher` (ISSUE 9).

    The previous implementation double-buffered on the HOST with one
    bare thread + event pair per iterator, and its ``reset()`` waited on
    a ``_ready`` event an exhausted/raising worker might never set again
    — a deadlock. Delegating buys: prefetch **to device** (numpy leaves
    upload while the consumer computes; pass ``prefetch_to_device=``
    a mesh ``Trainer`` or ``Sharding`` to land per-replica slices
    directly), depth > 1 (``MXTPU_PREFETCH_DEPTH``), worker errors
    re-raised at the consumer instead of vanishing, and a ``reset()``
    that joins the worker with a TIMEOUT and re-raises its pending
    exception."""

    def __init__(self, iters, rename_data=None, rename_label=None,
                 prefetch_to_device=None, depth=None):
        if not isinstance(iters, (list, tuple)):
            iters = [iters]
        super().__init__(iters[0].batch_size)
        self.iters = iters
        self.rename_data = rename_data
        self.rename_label = rename_label
        self._sharding_spec = prefetch_to_device
        self._depth = depth
        self._pending = None
        self._prefetcher = None
        self._start()

    @staticmethod
    def _pull(it):
        while True:
            try:
                yield it.next()
            except StopIteration:
                return

    def _merged(self, sources):
        while True:
            batches = []
            for src in sources:
                try:
                    batches.append(next(src))
                except StopIteration:
                    return
            data = sum((b.data for b in batches), [])
            label = sum((b.label or [] for b in batches), [])
            yield DataBatch(data=data, label=label or None,
                            pad=batches[0].pad, index=batches[0].index)

    def _start(self):
        from .stream import DevicePrefetcher
        self._pending = None
        # cross-iterator parallelism (the old implementation's
        # thread-per-iter, kept): with multiple sub-iterators each gets
        # its own producer stage decoding ahead, so per-batch source
        # latency is the MAX across iterators, not the SUM; the outer
        # stage merges, owns the target-sharding placement, and carries
        # the data.* telemetry
        # to_device=False: sub stages buffer on the HOST — the one H2D
        # copy (onto the target sharding) belongs to the outer stage, or
        # numpy batches would upload to the default device here and then
        # transfer AGAIN when the outer stage re-places them
        self._sub = [DevicePrefetcher(self._pull(it), depth=self._depth,
                                      site="data.sub", to_device=False)
                     for it in self.iters] if len(self.iters) > 1 else None
        self._prefetcher = DevicePrefetcher(
            self._merged(self._sub or [self._pull(self.iters[0])]),
            depth=self._depth, sharding=self._sharding_spec)

    @property
    def provide_data(self):
        out = []
        for i, it in enumerate(self.iters):
            descs = it.provide_data
            if self.rename_data:
                descs = [DataDesc(self.rename_data[i].get(d.name, d.name),
                                  d.shape, d.dtype) for d in descs]
            out.extend(descs)
        return out

    @property
    def provide_label(self):
        out = []
        for i, it in enumerate(self.iters):
            descs = it.provide_label
            if self.rename_label:
                descs = [DataDesc(self.rename_label[i].get(d.name, d.name),
                                  d.shape, d.dtype) for d in descs]
            out.extend(descs)
        return out

    def reset(self):
        # bounded join + reraise: an exhausted or raising underlying iter
        # must never deadlock the reset path (the old event-pair bug); a
        # worker error surfaces HERE rather than being dropped (sub-stage
        # errors propagate through the outer producer, so the outer close
        # carries them)
        try:
            self._prefetcher.close(timeout=5.0, reraise=True)
        finally:
            # even when the outer close raises, the sub producers must
            # die: a leaked sub keeps pulling its iterator in the
            # background (corrupting its cursor for any retry) and pins
            # its buffered batches — and with them gone, a retried
            # reset() starts from a clean slate
            for sub in self._sub or ():
                try:
                    sub.close(timeout=5.0)
                except Exception:  # noqa: BLE001 — teardown must not mask
                    pass
        for it in self.iters:
            it.reset()
        self._start()

    def next(self):
        if self._pending is not None:
            batch, self._pending = self._pending, None
            return batch
        return next(self._prefetcher)

    def iter_next(self):
        if self._pending is not None:
            return True
        try:
            self._pending = next(self._prefetcher)
        except StopIteration:
            return False
        return True

    def close(self, timeout=5.0):
        if self._prefetcher is not None:
            self._prefetcher.close(timeout=timeout)
        for sub in self._sub or ():
            sub.close(timeout=timeout)

    def __del__(self):  # pragma: no cover - interpreter-exit timing
        try:
            self.close(timeout=0.5)
        except Exception:  # noqa: BLE001
            pass


class CSVIter(DataIter):
    """CSV file iterator (ref: src/io/iter_csv.cc). Loads host-side with
    numpy; shapes must be given like the reference's data_shape param."""

    def __init__(self, data_csv, data_shape, label_csv=None, label_shape=(1,),
                 batch_size=1, round_batch=True, **kwargs):
        data = np.loadtxt(data_csv, delimiter=",", dtype=np.float32, ndmin=2)
        data = data.reshape((-1,) + tuple(data_shape))
        label = None
        if label_csv is not None:
            label = np.loadtxt(label_csv, delimiter=",", dtype=np.float32,
                               ndmin=2).reshape((-1,) + tuple(label_shape))
            if label.shape[-1] == 1:
                label = label.reshape(label.shape[:-1])
        self._inner = NDArrayIter(
            data, label, batch_size=batch_size,
            last_batch_handle="roll_over" if round_batch else "pad")
        super().__init__(batch_size)

    @property
    def provide_data(self):
        return self._inner.provide_data

    @property
    def provide_label(self):
        return self._inner.provide_label

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()


class LibSVMIter(DataIter):
    """Batched reader for LibSVM-format text (``label idx:val idx:val ...``)
    producing CSR data batches (ref: src/io/iter_libsvm.cc +
    iter_sparse_batchloader.h).

    TPU note: each batch is a CSRNDArray whose (data, indptr, indices) are
    dense arrays; downstream ``mx.nd.sparse.dot`` consumes them via
    gather/segment-sum with no dense (batch, num_features) materialization.
    Sharded reads via ``num_parts``/``part_index`` keep multi-host loading
    symmetrical (SURVEY §2.4).
    """

    def __init__(self, data_libsvm, data_shape, batch_size,
                 label_libsvm=None, num_parts=1, part_index=0,
                 round_batch=True, data_name="data",
                 label_name="softmax_label"):
        super().__init__(batch_size)
        self.data_shape = tuple(data_shape)
        self.data_name = data_name
        self.label_name = label_name
        self.round_batch = round_batch
        labels, rows = self._parse(data_libsvm, num_parts, part_index,
                                   want_label=label_libsvm is None)
        if label_libsvm is not None:
            labels, _ = self._parse(label_libsvm, num_parts, part_index,
                                    want_label=True)
        self.labels = np.asarray(labels, np.float32)
        self.rows = rows  # list of (indices int32[], values float32[])
        max_idx = max((int(r[0].max()) for r in rows if len(r[0])),
                      default=-1)
        if max_idx >= self.data_shape[0]:
            raise MXNetError(
                "LibSVMIter: feature index %d >= data_shape[0]=%d. LibSVM "
                "files are often 1-based — pass data_shape=(max_index+1,) "
                "(the reference uses zero-based indexing, iter_libsvm.cc)"
                % (max_idx, self.data_shape[0]))
        self.num_data = len(rows)
        if self.num_data < batch_size:
            raise MXNetError("LibSVMIter: fewer rows (%d) than batch_size"
                             % self.num_data)
        self.reset()

    @staticmethod
    def _parse(path, num_parts, part_index, want_label):
        labels = []
        rows = []
        with open(path) as f:
            for i, line in enumerate(f):
                if num_parts > 1 and i % num_parts != part_index:
                    continue
                parts = line.split()
                if not parts:
                    continue
                start = 0
                if want_label:
                    labels.append(float(parts[0]))
                    start = 1
                idx = []
                val = []
                for tok in parts[start:]:
                    k, _, v = tok.partition(":")
                    idx.append(int(k))
                    val.append(float(v))
                rows.append((np.asarray(idx, np.int32),
                             np.asarray(val, np.float32)))
        return labels, rows

    @property
    def provide_data(self):
        return [DataDesc(self.data_name,
                         (self.batch_size,) + self.data_shape, np.float32)]

    @property
    def provide_label(self):
        return [DataDesc(self.label_name, (self.batch_size,), np.float32)]

    def reset(self):
        self._cursor = -1
        self.num_batches = (self.num_data // self.batch_size
                            if not self.round_batch else
                            (self.num_data + self.batch_size - 1)
                            // self.batch_size)

    def iter_next(self):
        self._cursor += 1
        return self._cursor < self.num_batches

    def _batch_ids(self):
        start = self._cursor * self.batch_size
        # round_batch: the last partial batch wraps to the front
        return [(start + i) % self.num_data for i in range(self.batch_size)]

    def getdata(self):
        from ..ndarray.sparse import CSRNDArray

        ids = self._batch_ids()
        indptr = np.zeros(self.batch_size + 1, np.int32)
        idx_parts = []
        val_parts = []
        for i, r in enumerate(ids):
            indices, values = self.rows[r]
            indptr[i + 1] = indptr[i] + len(indices)
            idx_parts.append(indices)
            val_parts.append(values)
        indices = np.concatenate(idx_parts) if idx_parts else \
            np.zeros(0, np.int32)
        values = np.concatenate(val_parts) if val_parts else \
            np.zeros(0, np.float32)
        return [CSRNDArray(values, indptr, indices,
                           (self.batch_size,) + self.data_shape)]

    def getlabel(self):
        ids = self._batch_ids()
        return [array(self.labels[ids])]

    def getpad(self):
        start = self._cursor * self.batch_size
        remaining = self.num_data - start
        if remaining < self.batch_size:
            return self.batch_size - remaining
        return 0


class MNISTIter(NDArrayIter):
    """MNIST idx-ubyte iterator (ref: src/io/iter_mnist.cc:43-190).

    Reads the standard ``*-images-idx3-ubyte`` / ``*-labels-idx1-ubyte``
    files (gzipped accepted), normalizes pixels to [0, 1) by 1/256 like
    the reference (:184), emits (batch, 1, 28, 28) float32 — or
    (batch, 784) with ``flat=True`` — and supports the reference's
    shuffle/seed/part sharding params. Incomplete tail batches are
    dropped (the reference's Next() only serves full batches)."""

    def __init__(self, image="./train-images-idx3-ubyte",
                 label="./train-labels-idx1-ubyte", batch_size=128,
                 shuffle=True, flat=False, seed=0, silent=False,
                 num_parts=1, part_index=0, data_name="data",
                 label_name="softmax_label", **kwargs):
        # loud, not silent (same policy as ImageIter's option check): a
        # misspelled option must not quietly train with defaults
        allowed = {"prefetch_buffer", "dtype"}  # reference-compat no-ops
        unknown = set(kwargs) - allowed
        if unknown:
            raise MXNetError("MNISTIter: unknown options %s"
                             % sorted(unknown))
        import gzip
        import struct

        def _open(path):
            return gzip.open(path, "rb") if path.endswith(".gz") \
                else open(path, "rb")

        with _open(label) as f:
            struct.unpack(">II", f.read(8))
            labels = np.frombuffer(f.read(), dtype=np.uint8) \
                .astype(np.float32)
        with _open(image) as f:
            _, num, rows, cols = struct.unpack(">IIII", f.read(16))
            images = np.frombuffer(f.read(), dtype=np.uint8) \
                .reshape(num, 1, rows, cols).astype(np.float32) / 256.0
        if flat:
            images = images.reshape(num, rows * cols)
        if shuffle:
            order = np.random.RandomState(seed).permutation(num)
            images, labels = images[order], labels[order]
        per = num // num_parts
        lo = part_index * per
        hi = lo + per if num_parts > 1 else num
        images, labels = images[lo:hi], labels[lo:hi]
        if not silent:
            import logging
            logging.info("MNISTIter: load %d images, shuffle=%s, shape=%s",
                         images.shape[0], shuffle, images.shape)
        super().__init__(images, labels, batch_size, shuffle=False,
                         last_batch_handle="discard", data_name=data_name,
                         label_name=label_name)


def ImageRecordIter(path_imgrec=None, path_imgidx=None, data_shape=None,
                    batch_size=1, shuffle=False, preprocess_threads=0,
                    part_index=0, num_parts=1, label_width=1,
                    rand_crop=False, rand_mirror=False, resize=0,
                    mean_r=0.0, mean_g=0.0, mean_b=0.0,
                    std_r=0.0, std_g=0.0, std_b=0.0,
                    mean_img=None, data_name="data",
                    label_name="softmax_label", **kwargs):
    """The reference's registered ImageRecordIter spelling
    (src/io/iter_image_recordio_2.cc:736) as a thin constructor over
    :class:`mxtpu.image.ImageIter` — RecordIO shards + threaded
    decode/augment + part sharding, with the C++ iterator's flat
    per-channel mean/std params mapped onto the augmenter stack."""
    from ..image import ImageIter
    if mean_img is not None:
        raise MXNetError("mean_img binary files are not supported: pass "
                         "mean_r/mean_g/mean_b (or use mx.image.ImageIter "
                         "with a mean array)")
    aug_kwargs = {}
    if any((mean_r, mean_g, mean_b)):
        aug_kwargs["mean"] = np.array([mean_r, mean_g, mean_b], np.float32)
    if any((std_r, std_g, std_b)):
        aug_kwargs["std"] = np.array([std_r or 1.0, std_g or 1.0,
                                      std_b or 1.0], np.float32)
        # the normalize augmenter is keyed on mean; std alone must not
        # be silently dropped
        aug_kwargs.setdefault("mean", np.zeros(3, np.float32))
    if resize:
        aug_kwargs["resize"] = int(resize)
    if rand_crop:
        aug_kwargs["rand_crop"] = True
    if rand_mirror:
        aug_kwargs["rand_mirror"] = True
    aug_kwargs.update(kwargs)  # remaining augmenter options pass through
    return ImageIter(batch_size=batch_size, data_shape=data_shape,
                     label_width=label_width, path_imgrec=path_imgrec,
                     path_imgidx=path_imgidx, shuffle=shuffle,
                     part_index=part_index, num_parts=num_parts,
                     preprocess_threads=preprocess_threads,
                     data_name=data_name, label_name=label_name,
                     **aug_kwargs)
