"""mx.io: data iterators.

Reference: ``python/mxnet/io/io.py`` (DataDesc/DataBatch/DataIter/NDArrayIter)
and the C++ iterator chain (SURVEY §2.4: src/io/ — source → augmenter →
batch loader → prefetcher).
"""
from .io import (DataDesc, DataBatch, DataIter, NDArrayIter, ResizeIter,
                 PrefetchingIter, CSVIter, LibSVMIter, MNISTIter,
                 ImageRecordIter)

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "ResizeIter",
           "PrefetchingIter", "CSVIter", "LibSVMIter", "MNISTIter",
           "ImageRecordIter"]
