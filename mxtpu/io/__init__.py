"""mx.io: data iterators.

Reference: ``python/mxnet/io/io.py`` (DataDesc/DataBatch/DataIter/NDArrayIter)
and the C++ iterator chain (SURVEY §2.4: src/io/ — source → augmenter →
batch loader → prefetcher). The prefetcher stage is TPU-native here:
``mxtpu/io/stream.py`` holds the sharded streaming reader and the
double-buffered prefetch-to-device pipeline (ISSUE 9,
docs/data_pipeline.md).
"""
from .io import (DataDesc, DataBatch, DataIter, NDArrayIter, ResizeIter,
                 PrefetchingIter, CSVIter, LibSVMIter, MNISTIter,
                 ImageRecordIter)
from .stream import (DevicePrefetcher, ShardedRecordReader, StreamRecordIter,
                     shard_keys)

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "ResizeIter",
           "PrefetchingIter", "CSVIter", "LibSVMIter", "MNISTIter",
           "ImageRecordIter", "DevicePrefetcher", "ShardedRecordReader",
           "StreamRecordIter", "shard_keys"]
