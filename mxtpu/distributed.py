"""Multi-host distributed runtime (ref: the ps-lite worker/server stack).

The reference bootstraps distributed training through ps-lite: every worker
connects to a scheduler at DMLC_PS_ROOT_URI:DMLC_PS_ROOT_PORT with role/rank
from DMLC_ROLE/DMLC_NUM_WORKER (src/kvstore/kvstore_dist.h:44,
python/mxnet/kvstore_server.py:28-75, launcher tools/launch.py). Parameter
servers hold shards; workers push/pull over TCP.

TPU-native re-design: there are no parameter servers. Every process joins one
JAX distributed runtime (`jax.distributed.initialize`) and the global device
mesh then spans all hosts — XLA collectives ride ICI within a slice and DCN
across slices, and the same jitted ShardedTrainStep that does single-host
data parallelism becomes multi-host by construction (the mesh just has more
devices). This module is the bootstrap: the analog of kvstore_server.py's
role dance, reduced to one symmetric `init()`.

Env bootstrap accepts both spellings:

* ``MXTPU_COORDINATOR`` / ``MXTPU_NUM_PROCESSES`` / ``MXTPU_PROCESS_ID``
* reference names: ``DMLC_PS_ROOT_URI`` + ``DMLC_PS_ROOT_PORT`` /
  ``DMLC_NUM_WORKER`` / ``DMLC_WORKER_ID`` (tools/launch.py exports these)
"""
from __future__ import annotations

import os

import jax

from .base import MXNetError

__all__ = ["init", "is_initialized", "shutdown", "rank", "num_workers",
           "barrier", "global_compute_supported"]

_initialized = False


def _env_config():
    env = os.environ
    coord = env.get("MXTPU_COORDINATOR")
    if coord is None and env.get("DMLC_PS_ROOT_URI"):
        coord = "%s:%s" % (env["DMLC_PS_ROOT_URI"],
                           env.get("DMLC_PS_ROOT_PORT", "9091"))
    nproc = env.get("MXTPU_NUM_PROCESSES") or env.get("DMLC_NUM_WORKER")
    pid = env.get("MXTPU_PROCESS_ID")
    if pid is None:
        pid = env.get("DMLC_WORKER_ID")
    return coord, (int(nproc) if nproc else None), (int(pid) if pid else None)


def init(coordinator_address=None, num_processes=None, process_id=None,
         local_device_ids=None):
    """Join the distributed runtime. Idempotent; returns (rank, num_workers).

    With no arguments, reads the env bootstrap (see module docstring) — on
    Cloud TPU pods jax.distributed can also autodetect everything, so all
    arguments staying None there is fine too.
    """
    global _initialized
    if _initialized:
        return rank(), num_workers()
    # Adopt a runtime that is already up (jax.distributed autodetection on
    # Cloud TPU pods, or a framework that initialized before us): calling
    # jax.distributed.initialize() again would raise, and the module flag
    # alone cannot know about it.
    try:
        already = jax.distributed.is_initialized()
    except Exception:
        already = False
    if already:
        _initialized = True
        return rank(), num_workers()
    env_coord, env_n, env_id = _env_config()
    coordinator_address = coordinator_address or env_coord
    num_processes = num_processes if num_processes is not None else env_n
    process_id = process_id if process_id is not None else env_id
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids)
    _initialized = True
    return rank(), num_workers()


def is_initialized():
    """True when this process joined a multi-process runtime (or one was
    already active, e.g. via jax.distributed autodetection). Careful NOT to
    initialize the XLA backend while probing — jax.process_count() would,
    and afterwards jax.distributed.initialize() is impossible in this
    process, making any 'call init() first' advice unfollowable."""
    if _initialized:
        return True
    # public API first (side-effect free)
    try:
        if jax.distributed.is_initialized():
            return True
    except Exception:
        pass
    # TPU-runtime multi-host can be multi-process without an explicit
    # jax.distributed.initialize(). Probing that requires process_count(),
    # which would INITIALIZE the backend and break a later init() — so only
    # consult it when the backend is already up. backends_are_initialized is
    # private; tests/test_distributed.py pins its existence so a jax upgrade
    # fails loudly instead of silently flipping this answer (VERDICT r2
    # weak #7).
    try:
        from jax._src import xla_bridge as _xb
        backend_up = _xb.backends_are_initialized()
    except Exception:
        return False
    return backend_up and jax.process_count() > 1


def shutdown():
    global _initialized
    if _initialized:
        jax.distributed.shutdown()
        _initialized = False


def rank():
    """This process's id (ref: KVStore::get_rank / ps::MyRank)."""
    return jax.process_index()


def num_workers():
    """World size (ref: KVStore::get_group_size)."""
    return jax.process_count()


def global_compute_supported():
    """Whether this backend can run ONE computation spanning every
    process's devices. XLA:CPU cannot ("Multiprocess computations aren't
    implemented on the CPU backend"): the rendezvous service and
    host-side collectives work there, but any jit over a process-spanning
    mesh — including the psum behind :func:`barrier` — raises. The fleet
    tier consults this to fall back to per-host local meshes and
    filesystem barriers on the forced-CPU test tier; TPU/GPU fleets
    always report True."""
    return jax.process_count() <= 1 or jax.default_backend() != "cpu"


def barrier(name="mxtpu_barrier"):
    """Block until every process reaches the barrier (ref: KVStore::Barrier →
    ps Postoffice::Barrier). A tiny psum over all global devices is the
    rendezvous; it rides DCN across hosts."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(name)


def allgather_host(x):
    """Gather a host-local array from every process; returns [world, ...].
    Single-process returns x[None]."""
    import numpy as np
    if jax.process_count() <= 1:
        return np.asarray(x)[None]
    from jax.experimental import multihost_utils
    return np.asarray(multihost_utils.process_allgather(x))


def allreduce_host(x):
    """Sum a host-local numpy/jax array across all processes (the control
    plane's allreduce — the data plane's lives inside jitted steps). Returns
    the global sum as a host array; single-process is the identity."""
    if jax.process_count() <= 1:
        return x
    from jax.experimental import multihost_utils
    import numpy as np
    stacked = multihost_utils.process_allgather(x)
    return np.asarray(stacked).sum(axis=0)
