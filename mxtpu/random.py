"""Random state management.

Reference: per-ctx PRNG resources handed to ops via ResourceRequest{kRandom,
kParallelRandom} (include/mxnet/resource.h:38-56, src/resource.cc:87) and
``mx.random.seed``.

TPU-native re-design: JAX functional PRNG. A process-global key is split on every
draw (eager mode). Inside a traced/jitted computation (CachedOp / hybridized block),
drawing from a hidden global would bake the key into the compiled executable, so a
*key supply* can be pushed for the trace: the CachedOp passes a fresh key argument
each call and random ops split from it — keeping compiled dropout stochastic across
calls while staying purely functional.

Parallel PRNG (the reference's kParallelRandom resource, src/resource.cc:87 —
per-worker independent generator streams for data-parallel kernels): subsumed
by GSPMD semantics. Random HLOs trace against the GLOBAL logical tensor shape;
when the tensor is sharded over the mesh, XLA partitions the generator so each
position draws its unique stream regardless of which device materializes it —
per-device decorrelation needs no per-device resource objects, and a dropout
mask over a batch-sharded activation is automatically distinct on every shard
(tests/test_parallel.py exercises sharded-dropout training). Explicit
per-process decorrelation across multi-HOST data pipelines uses
``seed(s + rank)`` exactly like the reference's per-worker seeding.
"""
from __future__ import annotations

import threading

import jax
import jax.numpy as jnp

__all__ = ["seed", "next_key", "push_key_supply", "pop_key_supply",
           "get_key_data", "set_key_data"]


class _RngState(threading.local):
    def __init__(self):
        # lazy: materializing a key would initialize the XLA backend at
        # `import mxtpu` time, which must stay legal BEFORE
        # mxtpu.distributed.init() (jax.distributed refuses to start after
        # backend init)
        self.key = None
        self.supply = []  # stack of _KeySupply for active traces

    def base_key(self):
        if self.key is None:
            self.key = jax.random.key(0)
        return self.key


_STATE = _RngState()


class _KeySupply:
    """Deterministic splitter over a (possibly traced) base key."""

    def __init__(self, base_key):
        self.base = base_key
        self.count = 0

    def next(self):
        k = jax.random.fold_in(self.base, self.count)
        self.count += 1
        return k


def seed(seed_state, ctx="all"):
    """Seed the global generator (ref: mx.random.seed / MXRandomSeed)."""
    _STATE.key = jax.random.key(int(seed_state))
    _STATE.supply = []


def next_key():
    """Return a fresh PRNG key (the per-op kRandom resource acquisition)."""
    if _STATE.supply:
        return _STATE.supply[-1].next()
    _STATE.key, sub = jax.random.split(_STATE.base_key())
    return sub


def get_key_data():
    """Host snapshot of the global PRNG key (the checkpointable RNG state —
    resilience.ResilientLoop serializes this for bit-exact resume)."""
    import numpy as np
    return np.asarray(jax.random.key_data(_STATE.base_key()))


def set_key_data(data):
    """Restore the global PRNG key from :func:`get_key_data` output. Clears
    any active key supplies (a restore mid-trace would be a bug anyway)."""
    _STATE.key = jax.random.wrap_key_data(
        jnp.asarray(data, dtype=jnp.uint32))
    _STATE.supply = []


def push_key_supply(base_key) -> _KeySupply:
    s = _KeySupply(base_key)
    _STATE.supply.append(s)
    return s


def pop_key_supply():
    return _STATE.supply.pop()
