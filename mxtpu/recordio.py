"""mx.recordio: RecordIO file API.

Reference: ``python/mxnet/recordio.py`` — MXRecordIO / MXIndexedRecordIO over
the dmlc recordio C++ reader, plus pack/unpack(+_img) helpers with the IRHeader
struct.

TPU-native: the C++ backend lives in src/io/recordio.cc (compiled on demand,
ctypes-bound); a pure-python implementation of the same wire format is the
fallback so the API never hard-depends on the toolchain.
"""
from __future__ import annotations

import ctypes
import os
import struct
from collections import namedtuple

import numpy as np

from .base import MXNetError
from ._native import get_lib

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "IRHeader",
           "pack", "unpack", "pack_img", "unpack_img"]

_MAGIC = 0xced7230a
_MAGIC_BYTES = struct.pack("<I", _MAGIC)


# --------------------------------------------------------- python fallback
class _PyWriter:
    def __init__(self, path, mode):
        self._f = open(path, mode)

    def write(self, data):
        cuts = [i for i in range(0, len(data) - 3, 4)
                if data[i:i + 4] == _MAGIC_BYTES]
        if not cuts:
            self._chunk(0, data)
            return
        begin = 0
        for c, end in enumerate(cuts + [len(data)]):
            cflag = 1 if c == 0 else (3 if end == len(data) else 2)
            self._chunk(cflag, data[begin:end])
            begin = end + 4

    def _chunk(self, cflag, data):
        lrec = (cflag << 29) | len(data)
        self._f.write(_MAGIC_BYTES)
        self._f.write(struct.pack("<I", lrec))
        self._f.write(data)
        pad = (4 - (len(data) & 3)) & 3
        self._f.write(b"\x00" * pad)

    def tell(self):
        return self._f.tell()

    def close(self):
        self._f.close()


class _PyReader:
    def __init__(self, path):
        self._f = open(path, "rb")

    corrupt = False  # set when read() stops on damage rather than clean EOF

    def _walk(self, read):
        """ONE record-framing walk (magic check, cflag chunk state
        machine, pad skip, corrupt flags) shared by the sequential and
        positioned paths — ``read(n)`` supplies the next n bytes and owns
        its own position, so the two readers can never diverge on
        framing."""
        out = b""
        started = False
        while True:
            head = read(8)
            if len(head) == 0 and not started:
                return None  # clean EOF at a record boundary
            if len(head) < 8:
                self.corrupt = True  # truncated mid-header
                return None
            magic, lrec = struct.unpack("<II", head)
            if magic != _MAGIC:
                self.corrupt = True  # lost sync
                return None
            length, cflag = lrec & ((1 << 29) - 1), lrec >> 29
            data = read(length)
            if len(data) < length:
                self.corrupt = True  # truncated mid-payload: NOT a record
                return None
            pad = (4 - (length & 3)) & 3
            if pad:
                read(pad)
            out += data
            if cflag == 0 or cflag == 3:
                return out
            if cflag == 1:
                started = True
            elif not started:
                return None
            out += _MAGIC_BYTES  # re-insert elided magic between chunks

    def read(self):
        return self._walk(self._f.read)

    def read_at(self, pos):
        """Positioned read of ONE logical record starting at byte ``pos``
        (pread-style: the handle's shared seek offset is never touched, so
        any number of concurrent shard readers can share one open file
        with no seek races and no lock). Same framing walk as
        :meth:`read` by construction (``_walk``); the sequential path
        stays byte-identical (pinned by round-trip test)."""
        fd = self._f.fileno()
        state = {"pos": pos}

        def pread(n):
            b = os.pread(fd, n, state["pos"])
            state["pos"] += len(b)
            return b

        return self._walk(pread)

    def seek(self, pos):
        self._f.seek(pos)

    def tell(self):
        return self._f.tell()

    def close(self):
        self._f.close()


# ----------------------------------------------------------------- MXRecordIO
class MXRecordIO:
    """Sequential RecordIO reader/writer (ref: recordio.py:MXRecordIO)."""

    def __init__(self, uri, flag):
        self.uri = uri
        self.flag = flag
        self.handle = None
        self.is_open = False
        self.open()

    def open(self):
        lib = get_lib()
        self._lib = lib
        if self.flag == "w":
            if lib is not None:
                self.handle = lib.mxtpu_recordio_writer_create(
                    self.uri.encode(), b"wb")
                if not self.handle:
                    raise MXNetError("cannot open %s" % self.uri)
            else:
                self.handle = _PyWriter(self.uri, "wb")
            self.writable = True
        elif self.flag == "r":
            if lib is not None:
                self.handle = lib.mxtpu_recordio_reader_create(
                    self.uri.encode())
                if not self.handle:
                    raise MXNetError("cannot open %s" % self.uri)
            else:
                self.handle = _PyReader(self.uri)
            self.writable = False
        else:
            raise MXNetError("invalid flag %s" % self.flag)
        self.is_open = True

    def close(self):
        if not self.is_open:
            return
        if self._lib is not None:
            if self.writable:
                self._lib.mxtpu_recordio_writer_close(self.handle)
            else:
                self._lib.mxtpu_recordio_reader_close(self.handle)
        else:
            self.handle.close()
        self.is_open = False
        self.handle = None

    def __del__(self):
        self.close()

    def __getstate__(self):
        """Pickling support for multi-worker loaders (ref: recordio.py)."""
        d = dict(self.__dict__)
        d["handle"] = None
        d["_lib"] = None
        d["is_open"] = False
        d.pop("_rw_lock", None)  # locks don't pickle; recreated in __setstate__
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)
        if hasattr(self, "idx_path"):
            import threading
            self._rw_lock = threading.Lock()
        self.open()

    def reset(self):
        self.close()
        self.open()

    def write(self, buf):
        assert self.writable
        if self._lib is not None:
            rc = self._lib.mxtpu_recordio_writer_write(
                self.handle, bytes(buf), len(buf))
            if rc != 0:
                raise MXNetError("write failed on %s" % self.uri)
        else:
            self.handle.write(bytes(buf))

    def read(self):
        assert not self.writable
        if self._lib is not None:
            n = ctypes.c_uint64()
            ptr = self._lib.mxtpu_recordio_reader_read(
                self.handle, ctypes.byref(n))
            if not ptr:
                return None
            return ctypes.string_at(ptr, n.value)
        return self.handle.read()

    def tell(self):
        if self._lib is not None:
            if self.writable:
                return int(self._lib.mxtpu_recordio_writer_tell(self.handle))
            return int(self._lib.mxtpu_recordio_reader_tell(self.handle))
        return self.handle.tell()


class MXIndexedRecordIO(MXRecordIO):
    """Keyed random access via a .idx sidecar (ref: recordio.py:
    MXIndexedRecordIO)."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        import threading
        # seek+read must be atomic: thread-pool DataLoader workers share this
        # handle (the reference instead forks a process per worker)
        self._rw_lock = threading.Lock()
        super().__init__(uri, flag)

    def open(self):
        super().open()
        self.idx = {}
        self.keys = []
        if not self.writable and os.path.isfile(self.idx_path):
            with open(self.idx_path) as fin:
                for line in fin:
                    parts = line.strip().split("\t")
                    if len(parts) < 2:
                        continue
                    key = self.key_type(parts[0])
                    self.idx[key] = int(parts[1])
                    self.keys.append(key)

    def close(self):
        if not self.is_open:
            return
        if self.writable:
            with open(self.idx_path, "w") as fout:
                for key in self.keys:
                    fout.write("%s\t%d\n" % (str(key), self.idx[key]))
        super().close()

    def seek(self, idx):
        assert not self.writable
        pos = self.idx[idx]
        if self._lib is not None:
            self._lib.mxtpu_recordio_reader_seek(self.handle, pos)
        else:
            self.handle.seek(pos)

    def read_idx(self, idx):
        with self._rw_lock:
            self.seek(idx)
            return self.read()

    def pread_idx(self, idx):
        """Positioned keyed read. On the python reader this is a true
        pread (``_PyReader.read_at`` — no shared offset mutated, no lock:
        the streaming shard readers in ``mxtpu/io/stream.py`` fan any
        number of threads over ONE open handle). The native reader keeps
        its internal cursor, so it degrades to the locked seek+read."""
        assert not self.writable
        if self._lib is None:
            return self.handle.read_at(self.idx[idx])
        return self.read_idx(idx)

    def write_idx(self, idx, buf):
        assert self.writable
        key = self.key_type(idx)
        pos = self.tell()
        self.write(buf)
        self.keys.append(key)
        self.idx[key] = pos


# ------------------------------------------------------------- pack helpers
IRHeader = namedtuple("HEADER", ["flag", "label", "id", "id2"])
_IR_FORMAT = "IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


def pack(header, s):
    """Pack a header + raw bytes (ref: recordio.py:pack). flag>0 means the
    label is a float array of that length stored before the payload."""
    header = IRHeader(*header)
    if isinstance(header.label, (np.ndarray, list, tuple)):
        label = np.asarray(header.label, dtype=np.float32)
        header = header._replace(flag=label.size, label=0)
        s = label.tobytes() + s
    return struct.pack(_IR_FORMAT, *header) + s


def unpack(s):
    """Inverse of pack: returns (IRHeader, payload bytes)."""
    header = IRHeader(*struct.unpack(_IR_FORMAT, s[:_IR_SIZE]))
    s = s[_IR_SIZE:]
    if header.flag > 0:
        label = np.frombuffer(s[:header.flag * 4], dtype=np.float32)
        header = header._replace(label=label)
        s = s[header.flag * 4:]
    return header, s


def pack_img(header, img, quality=95, img_fmt=".jpg"):
    """JPEG/PNG-encode an image and pack (ref: recordio.py:pack_img)."""
    import cv2
    encode_params = None
    if img_fmt.lower() in (".jpg", ".jpeg"):
        encode_params = [cv2.IMWRITE_JPEG_QUALITY, quality]
    elif img_fmt.lower() == ".png":
        encode_params = [cv2.IMWRITE_PNG_COMPRESSION, quality]
    ret, buf = cv2.imencode(img_fmt, img, encode_params)
    if not ret:
        raise MXNetError("failed to encode image")
    return pack(header, buf.tobytes())


def unpack_img(s, iscolor=-1):
    """Unpack + decode an image record (ref: recordio.py:unpack_img).
    Returns (IRHeader, HWC BGR ndarray like the reference's cv2 convention)."""
    import cv2
    header, s = unpack(s)
    img = cv2.imdecode(np.frombuffer(s, dtype=np.uint8), iscolor)
    return header, img
