"""Unified runtime telemetry: metrics registry, step-phase timeline, watchdogs.

The reference framework's ops-facing surface is its engine-level profiler
(src/profiler/profiler.h: per-op events, queue time, chrome-trace dump,
aggregate tables). On a jit-compiled TPU stack the signals that matter are
different — recompiles, host syncs, kernel-dispatch routing, skip-steps,
IO retries — and before this module they were scattered across five
modules (``optimizer_fused.FUSED_STATS``, ``ops.pallas.conv.
DISPATCH_STATS``, ``resilience.FAULT_STATS``, monitor logs, bench-only
counters) with no common surface. This module is that surface:

* **Registry** — process-global counters / gauges / histograms with
  near-zero-overhead host-side updates (one short lock, no device work,
  no syncs — safe inside a ``jax.transfer_guard``), ``snapshot()`` for a
  structured view and ``report()`` for the aggregate table.
* **Spans** — ``with telemetry.span("trainer.step"): ...`` times a host
  region into a histogram AND a bounded event ring that
  :func:`mxtpu.profiler.dump` merges into the chrome-trace JSON, so one
  file shows the host step phases alongside the XLA trace.
* **Retrace watchdog** — jit-cache owners (``optimizer_fused.
  FusedUpdater``, gluon ``CachedOp``) report every compile with its
  cache-key / ``registry.policy_key`` provenance via
  :func:`record_retrace`; once a site exceeds ``MXTPU_RETRACE_BUDGET``
  compiles the watchdog warns with the provenance — steady-state
  recompiles are where jit-stack performance silently dies (PyGraph's
  core lesson: graph-capture systems fail without first-class re-capture
  accounting).
* **Transfer watchdog** — ``NDArray.asnumpy``-class device->host syncs
  bump a global counter; a ``span(..., d2h=True)`` attributes the delta
  to its region (``<name>.d2h``) and warns when a steady-state hot-loop
  region syncs at all. This generalizes the transfer-guard TEST machinery
  of the resilience PR into an always-available production counter.
* **JSON-lines sink** — ``MXTPU_TELEMETRY=<path>`` streams observations
  (and cumulative counters at flush) to a JSONL file; flushing is
  off-thread (``MXTPU_TELEMETRY_FLUSH_S``) and OFF by default — the hot
  path only ever appends to an in-memory deque.
  ``tools/telemetry_report.py`` turns the file into the aggregate table.

Gating: ``MXTPU_TELEMETRY=0`` disables the span/event/sink machinery
(timers, ring appends). Plain counter/gauge increments stay always-on —
they are single dict updates, and the adopted stats views
(``DISPATCH_STATS`` etc.) must keep working regardless of the lever.
"""
from __future__ import annotations

import collections
import json
import logging
import os
import threading
import time

__all__ = ["enabled", "retrace_budget", "inc", "gauge", "observe", "value",
           "tagged", "reset_metric", "span", "record_d2h", "d2h_count",
           "record_retrace", "retrace_stats", "snapshot", "report",
           "events", "flush", "jsonl_path", "reset"]

_log = logging.getLogger("mxtpu.telemetry")

# one short lock for every structural update; individual increments hold it
# for nanoseconds (the "lock-cheap host-side increment" contract)
_LOCK = threading.Lock()
_COUNTERS = {}            # (name, tag-or-None) -> float
_GAUGES = {}              # name -> float
_HISTS = {}               # name -> [count, sum, min, max, reservoir-deque]
_EVENTS = collections.deque(maxlen=65536)  # (name, cat, ts_us, dur_us, tid)
_RESERVOIR = 2048         # per-histogram quantile sample bound

# retrace watchdog: site -> {"compiles", "trips", "last"}
_RETRACE = {}
# transfer watchdog: hot-loop span names already warned about
_D2H_WARNED = set()
_D2H_WARMUP = 2           # first occurrences of a span may legitimately sync


class _D2HLocal(threading.local):
    """Per-thread d2h sync count. Span attribution reads THIS, not the
    global counter: a span times a host region on its own thread, so a
    concurrent server thread's ``asnumpy`` (the serving fetch path) must
    not land in another thread's ``<name>.d2h`` delta. The global
    ``transfer.d2h`` counter still aggregates every thread."""

    def __init__(self):
        self.count = 0


_D2H_LOCAL = _D2HLocal()

# JSONL sink: hot path appends to the queue; a flush (explicit, atexit, or
# the off-thread timer) drains it to the file
_SINK = {"queue": collections.deque(maxlen=1 << 20), "thread": None,
         "atexit": False, "lock": threading.Lock()}


# ------------------------------------------------------------------ policies
def enabled():
    """Span/event/sink machinery lever: ``MXTPU_TELEMETRY`` default ON
    (read per call, like every other A/B lever, so bench can flip it
    mid-process). ``0`` disables spans; bare counters stay always-on."""
    return os.environ.get("MXTPU_TELEMETRY", "1") != "0"


def jsonl_path():
    """``MXTPU_TELEMETRY`` doubles as the sink switch: any value other
    than ``0``/``1`` is a JSONL path observations stream to."""
    v = os.environ.get("MXTPU_TELEMETRY", "1")
    return v if v not in ("0", "1") else None


def retrace_budget():
    """Compiles a single jit-cache site may accumulate before the retrace
    watchdog warns (``MXTPU_RETRACE_BUDGET``, default 64 — far above any
    legitimate warmup, low enough to catch a per-step recompile within
    the first minute)."""
    return int(os.environ.get("MXTPU_RETRACE_BUDGET", "64"))


def _flush_interval():
    """Off-thread flush period in seconds (``MXTPU_TELEMETRY_FLUSH_S``);
    0 (default) = no background thread — flush happens on
    :func:`flush` and at interpreter exit."""
    try:
        return float(os.environ.get("MXTPU_TELEMETRY_FLUSH_S", "0"))
    except ValueError:
        return 0.0


# ----------------------------------------------------------------- registry
def inc(name, n=1, tag=None):
    """Add ``n`` to a counter. ``tag`` keys a labeled sub-counter (e.g.
    pallas fallback reasons). Always-on: a single locked dict update."""
    k = (name, tag)
    with _LOCK:
        _COUNTERS[k] = _COUNTERS.get(k, 0) + n


def gauge(name, v):
    """Set a gauge to the latest value (last-write-wins)."""
    with _LOCK:
        _GAUGES[name] = float(v)


def observe(name, v):
    """Record one histogram observation (span durations land here)."""
    v = float(v)
    with _LOCK:
        h = _HISTS.get(name)
        if h is None:
            h = [0, 0.0, v, v, collections.deque(maxlen=_RESERVOIR)]
            _HISTS[name] = h
        h[0] += 1
        h[1] += v
        h[2] = min(h[2], v)
        h[3] = max(h[3], v)
        h[4].append(v)
    p = jsonl_path()
    if p is not None:
        _queue_line({"t": time.time(), "kind": "obs", "metric": name,
                     "value": v}, p)


def value(name, tag=None):
    """Current counter value (0 when never incremented); with no ``tag``
    and no untagged entry, the sum across tags."""
    with _LOCK:
        v = _COUNTERS.get((name, tag))
        if v is not None or tag is not None:
            return v or 0
        return sum(v for (n, t), v in _COUNTERS.items()
                   if n == name and t is not None) or 0


def tagged(name):
    """``{tag: value}`` over a labeled counter family."""
    with _LOCK:
        return {t: v for (n, t), v in _COUNTERS.items()
                if n == name and t is not None}


def reset_metric(name):
    """Zero one metric (counters incl. tags, gauge, histogram) — the
    adopted stats views (``reset_dispatch_stats``) use this; it must NOT
    clear the rest of the registry."""
    with _LOCK:
        for k in [k for k in _COUNTERS if k[0] == name]:
            del _COUNTERS[k]
        _GAUGES.pop(name, None)
        _HISTS.pop(name, None)


def _quantile(sorted_vals, q):
    n = len(sorted_vals)
    if n == 0:
        return None
    pos = q * (n - 1)
    lo = int(pos)
    hi = min(lo + 1, n - 1)
    frac = pos - lo
    return sorted_vals[lo] * (1 - frac) + sorted_vals[hi] * frac


def snapshot():
    """Structured aggregate view of everything the registry holds."""
    with _LOCK:
        by_name = {}
        for (name, tag), v in _COUNTERS.items():
            by_name.setdefault(name, {})[tag] = v
        # pure-untagged collapses to a scalar; a name incremented BOTH
        # ways keeps every entry (untagged under "_untagged") — mixing
        # must not silently drop either form from the aggregate view
        counters = {}
        for name, tags in by_name.items():
            if set(tags) == {None}:
                counters[name] = tags[None]
            else:
                counters[name] = {
                    ("_untagged" if t is None else t): v
                    for t, v in tags.items()}
        gauges = dict(_GAUGES)
        hists = {}
        for name, (cnt, total, mn, mx, res) in _HISTS.items():
            vals = sorted(res)
            hists[name] = {"count": cnt, "sum": total, "mean": total / cnt,
                           "min": mn, "max": mx,
                           "p50": _quantile(vals, 0.5),
                           "p99": _quantile(vals, 0.99)}
        retrace = {site: dict(st) for site, st in _RETRACE.items()}
    return {"counters": counters, "gauges": gauges, "histograms": hists,
            "retrace": retrace}


def report():
    """The aggregate table, profiler-dumps style: one call shows guard
    activity, dispatch routing, retries, and the step-phase timing without
    a log scrape."""
    snap = snapshot()
    lines = []
    if snap["histograms"]:
        lines.append("%-38s %8s %10s %10s %10s %10s" %
                     ("Span/Histogram", "Count", "Mean(ms)", "P50(ms)",
                      "P99(ms)", "Max(ms)"))
        for name in sorted(snap["histograms"],
                           key=lambda n: -snap["histograms"][n]["sum"]):
            h = snap["histograms"][name]
            lines.append("%-38s %8d %10.3f %10.3f %10.3f %10.3f" %
                         (name, h["count"], h["mean"] * 1e3,
                          (h["p50"] or 0) * 1e3, (h["p99"] or 0) * 1e3,
                          h["max"] * 1e3))
    if snap["counters"]:
        lines.append("")
        lines.append("%-38s %12s" % ("Counter", "Value"))
        for name in sorted(snap["counters"]):
            v = snap["counters"][name]
            if isinstance(v, dict):
                for tag in sorted(v):
                    lines.append("%-38s %12g" %
                                 ("%s{%s}" % (name, tag), v[tag]))
            else:
                lines.append("%-38s %12g" % (name, v))
    if snap["gauges"]:
        lines.append("")
        lines.append("%-38s %12s" % ("Gauge", "Value"))
        for name in sorted(snap["gauges"]):
            lines.append("%-38s %12g" % (name, snap["gauges"][name]))
    if snap["retrace"]:
        lines.append("")
        lines.append("%-20s %9s %6s  %s" %
                     ("Retrace site", "Compiles", "Trips", "Last provenance"))
        for site in sorted(snap["retrace"]):
            st = snap["retrace"][site]
            lines.append("%-20s %9d %6d  %s" %
                         (site, st["compiles"], st["trips"],
                          st["last"]))
    return "\n".join(lines) if lines else "(telemetry registry empty)"


def events():
    """The bounded span-event ring — (name, cat, ts_us, dur_us, tid)
    tuples on the ``time.perf_counter_ns`` clock, the SAME clock and
    shape :mod:`mxtpu.profiler` records op events with, so
    ``profiler.dump()`` merges them into one chrome trace."""
    with _LOCK:
        return list(_EVENTS)


def reset():
    """Test hook: clear the whole registry, event ring, and watchdog
    state (the sink file, if any, is left alone)."""
    with _LOCK:
        _COUNTERS.clear()
        _GAUGES.clear()
        _HISTS.clear()
        _EVENTS.clear()
        _RETRACE.clear()
        _D2H_WARNED.clear()


# -------------------------------------------------------------------- spans
class span:
    """Context manager timing a host-side region into the histogram
    ``name`` (seconds) and the chrome-trace event ring. ``d2h=True``
    additionally attributes device->host syncs observed inside the region
    to ``<name>.d2h`` and arms the transfer watchdog: a steady-state
    occurrence (past the first ``_D2H_WARMUP``) that syncs at all warns
    once — the guarded hot loop's contract is ZERO.

    Pure host bookkeeping: no device ops, no syncs — safe under a
    ``jax.transfer_guard`` and inside the zero-sync Trainer.step contract.
    The enter/exit pair is hand-tuned for sub-millisecond hot loops: ONE
    env read (lever + sink path resolved together), ONE lock acquisition
    on exit (histogram + event ring inline), lock-free d2h snapshot.
    """

    __slots__ = ("name", "cat", "_d2h", "_t0", "_d0", "_sink")

    def __init__(self, name, cat="phase", d2h=False):
        self.name = name
        self.cat = cat
        self._d2h = d2h
        self._t0 = None
        self._d0 = None
        self._sink = None

    def __enter__(self):
        lever = os.environ.get("MXTPU_TELEMETRY", "1")
        if lever != "0":
            self._sink = lever if lever != "1" else None
            self._t0 = time.perf_counter_ns()
            if self._d2h:
                # thread-local snapshot: only syncs issued by THIS thread
                # inside the region are attributed — concurrent server
                # threads cannot corrupt another span's delta
                self._d0 = _D2H_LOCAL.count
        return self

    def __exit__(self, *exc):
        t0 = self._t0
        if t0 is None:
            return False
        dur_ns = time.perf_counter_ns() - t0
        v = dur_ns * 1e-9
        name = self.name
        with _LOCK:
            h = _HISTS.get(name)
            if h is None:
                h = [0, 0.0, v, v, collections.deque(maxlen=_RESERVOIR)]
                _HISTS[name] = h
            h[0] += 1
            h[1] += v
            if v < h[2]:
                h[2] = v
            if v > h[3]:
                h[3] = v
            h[4].append(v)
            occurrences = h[0]
            _EVENTS.append((name, self.cat, t0 // 1000, dur_ns // 1000,
                            threading.get_ident() & 0xFFFF))
        if self._sink is not None:
            _queue_line({"t": time.time(), "kind": "obs", "metric": name,
                         "value": v}, self._sink)
        if self._d0 is not None:
            delta = _D2H_LOCAL.count - self._d0
            if delta:
                inc(name + ".d2h", delta)
                self._watchdog(delta, occurrences)
        self._t0 = None
        return False

    def _watchdog(self, delta, occurrences):
        with _LOCK:
            if occurrences <= _D2H_WARMUP or self.name in _D2H_WARNED:
                return
            _D2H_WARNED.add(self.name)
        _log.warning(
            "transfer watchdog: %d device->host sync(s) inside '%s' after "
            "warmup (occurrence %d) — the hot loop should be transfer-free; "
            "fetch verdicts/metrics asynchronously off the step path "
            "(docs/observability.md)", delta, self.name, occurrences)


# -------------------------------------------------------- transfer watchdog
def record_d2h(n=1):
    """Called from the NDArray sync points (``asnumpy`` and friends): one
    global device->host sync counter, always on, plus a thread-local count
    — spans opened with ``d2h=True`` attribute the THREAD-LOCAL delta to
    their region, so concurrent server threads (``mxtpu.serving``) cannot
    pollute the hot loop's per-region attribution."""
    inc("transfer.d2h", n)
    _D2H_LOCAL.count += n


def d2h_count():
    return value("transfer.d2h")


# --------------------------------------------------------- retrace watchdog
def record_retrace(site, provenance=None):
    """Report one jit-cache compile at ``site`` with its cache-key
    provenance (optimizer class, ``registry.policy_key`` tuple, ...).
    Counts into ``retrace.<site>``; past :func:`retrace_budget` compiles
    the watchdog warns with the provenance and bumps
    ``retrace.watchdog_trips`` — a steady-state recompile means a policy
    env flipped mid-run or a cache key is unstable (shapes/hyper leaking
    into the static config), both of which silently serialize training
    behind the compiler."""
    inc("retrace." + site)
    budget = retrace_budget()
    with _LOCK:
        st = _RETRACE.setdefault(site,
                                 {"compiles": 0, "trips": 0, "last": None})
        st["compiles"] += 1
        st["last"] = provenance
        over = st["compiles"] > budget
        if over:
            st["trips"] += 1
        compiles = st["compiles"]
        trips = st["trips"]
    if over:
        inc("retrace.watchdog_trips")
        # rate-limit the LOG (the trip counter stays exact): the target
        # pathology is a recompile every step — warning each time would
        # flood hours of logs with the message meant to make them readable
        if trips != 1 and trips % 100 != 0:
            return
        _log.warning(
            "retrace watchdog: '%s' compiled %d times, over "
            "MXTPU_RETRACE_BUDGET=%d. Last provenance: %s. Steady-state "
            "recompiles usually mean a policy env var flipped mid-run or "
            "an unstable cache key — each one stalls every step behind "
            "the compiler (docs/observability.md)",
            site, compiles, budget, provenance)


def retrace_stats(site=None):
    """Watchdog state: ``{site: {compiles, trips, last}}`` (or one
    site's dict / None)."""
    with _LOCK:
        if site is not None:
            st = _RETRACE.get(site)
            return dict(st) if st else None
        return {s: dict(st) for s, st in _RETRACE.items()}


# --------------------------------------------------------------- JSONL sink
def _queue_line(rec, path):
    _SINK["queue"].append((path, rec))
    if not _SINK["atexit"]:
        with _SINK["lock"]:
            if not _SINK["atexit"]:
                _SINK["atexit"] = True
                import atexit
                atexit.register(flush)
    interval = _flush_interval()
    if interval > 0 and _SINK["thread"] is None:
        with _SINK["lock"]:
            if _SINK["thread"] is None:
                t = threading.Thread(target=_flush_loop, args=(interval,),
                                     daemon=True, name="mxtpu-telemetry")
                _SINK["thread"] = t
                t.start()


def _flush_loop(interval):
    while True:
        time.sleep(interval)
        try:
            flush()
        except Exception:  # noqa: BLE001 — a sink error must never kill
            pass           # the flusher (next interval retries)


def flush():
    """Drain queued observations to the JSONL sink and append one
    cumulative line per counter/gauge. Off the hot path by construction
    (explicit call, atexit, or the off-thread timer)."""
    path = jsonl_path()
    lines_by_path = {}
    while True:
        try:
            p, rec = _SINK["queue"].popleft()
        except IndexError:
            break
        lines_by_path.setdefault(p, []).append(rec)
    if path is not None:
        now = time.time()
        with _LOCK:
            for (name, tag), v in _COUNTERS.items():
                rec = {"t": now, "kind": "counter", "metric": name,
                       "value": v}
                if tag is not None:
                    rec["tag"] = tag
                lines_by_path.setdefault(path, []).append(rec)
            for name, v in _GAUGES.items():
                lines_by_path.setdefault(path, []).append(
                    {"t": now, "kind": "gauge", "metric": name, "value": v})
    with _SINK["lock"]:
        for p, recs in lines_by_path.items():
            try:
                with open(p, "a") as f:
                    for rec in recs:
                        f.write(json.dumps(rec) + "\n")
            except OSError as e:  # pragma: no cover - sink IO failure
                _log.warning("telemetry sink write to %s failed: %s", p, e)
