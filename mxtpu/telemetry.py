"""Unified runtime telemetry: metrics registry, step-phase timeline, watchdogs.

The reference framework's ops-facing surface is its engine-level profiler
(src/profiler/profiler.h: per-op events, queue time, chrome-trace dump,
aggregate tables). On a jit-compiled TPU stack the signals that matter are
different — recompiles, host syncs, kernel-dispatch routing, skip-steps,
IO retries — and before this module they were scattered across five
modules (``optimizer_fused.FUSED_STATS``, ``ops.pallas.conv.
DISPATCH_STATS``, ``resilience.FAULT_STATS``, monitor logs, bench-only
counters) with no common surface. This module is that surface:

* **Registry** — process-global counters / gauges / histograms with
  near-zero-overhead host-side updates (one short lock, no device work,
  no syncs — safe inside a ``jax.transfer_guard``), ``snapshot()`` for a
  structured view and ``report()`` for the aggregate table.
* **Spans** — ``with telemetry.span("trainer.step"): ...`` times a host
  region into a histogram AND a bounded event ring that
  :func:`mxtpu.profiler.dump` merges into the chrome-trace JSON, so one
  file shows the host step phases alongside the XLA trace.
* **Retrace watchdog** — jit-cache owners (``optimizer_fused.
  FusedUpdater``, gluon ``CachedOp``) report every compile with its
  cache-key / ``registry.policy_key`` provenance via
  :func:`record_retrace`; once a site exceeds ``MXTPU_RETRACE_BUDGET``
  compiles the watchdog warns with the provenance — steady-state
  recompiles are where jit-stack performance silently dies (PyGraph's
  core lesson: graph-capture systems fail without first-class re-capture
  accounting).
* **Transfer watchdog** — ``NDArray.asnumpy``-class device->host syncs
  bump a global counter; a ``span(..., d2h=True)`` attributes the delta
  to its region (``<name>.d2h``) and warns when a steady-state hot-loop
  region syncs at all. This generalizes the transfer-guard TEST machinery
  of the resilience PR into an always-available production counter.
* **JSON-lines sink** — ``MXTPU_TELEMETRY=<path>`` streams observations
  (and cumulative counters at flush) to a JSONL file; flushing is
  off-thread (``MXTPU_TELEMETRY_FLUSH_S``) and OFF by default — the hot
  path only ever appends to an in-memory deque.
  ``tools/telemetry_report.py`` turns the file into the aggregate table.
* **Causal tracing** — a :class:`TraceContext` (trace id + span id)
  carried in a ``contextvars.ContextVar`` so nested :class:`span` calls
  build per-request / per-step trees, with an EXPLICIT handoff API
  (:func:`trace_handoff`) for crossing threads: batcher dispatch workers,
  replica re-dispatches, and prefetch producers adopt the originating
  trace instead of losing it at the thread boundary. A bounded trace
  ring feeds the **flight recorder** (:func:`flight_record`): on
  watchdog trips, breaker opens, injected faults, and SIGTERM a JSON
  artifact with the recent trace events + per-thread stacks is written
  to ``MXTPU_FLIGHT_DIR``, so post-mortems need no live repro.
  ``MXTPU_TRACE=0`` turns the trace layer off (spans keep timing).
* **Prometheus exposition** — :func:`prometheus` renders the whole
  registry in the text exposition format; the model server
  content-negotiates it on ``/metrics`` next to the JSON snapshot.

Gating: ``MXTPU_TELEMETRY=0`` disables the span/event/sink machinery
(timers, ring appends). Plain counter/gauge increments stay always-on —
they are single dict updates, and the adopted stats views
(``DISPATCH_STATS`` etc.) must keep working regardless of the lever.
"""
from __future__ import annotations

import collections
import contextvars
import itertools
import json
import logging
import os
import threading
import time

__all__ = ["enabled", "retrace_budget", "inc", "gauge", "observe", "value",
           "tagged", "gauge_value", "reset_metric", "span",
           "record_d2h", "d2h_count",
           "record_retrace", "retrace_stats", "snapshot", "report",
           "events", "flush", "jsonl_path", "reset",
           "tracing_enabled", "TraceContext", "new_trace", "current_trace",
           "trace_handoff", "add_stage", "trace_mark", "link", "pend_link",
           "link_pending", "trace_breakdown", "trace_events", "trace_flows",
           "flight_record", "flight_snapshot", "prometheus",
           "on_flush", "register_prometheus_extra"]

_log = logging.getLogger("mxtpu.telemetry")

# one short lock for every structural update; individual increments hold it
# for nanoseconds (the "lock-cheap host-side increment" contract)
_LOCK = threading.Lock()
_COUNTERS = {}            # (name, tag-or-None) -> float
_GAUGES = {}              # name -> float
_HISTS = {}               # name -> [count, sum, min, max, reservoir-deque]
_EVENTS = collections.deque(maxlen=65536)  # (name, cat, ts_us, dur_us, tid)
_RESERVOIR = 2048         # per-histogram quantile sample bound

# retrace watchdog: site -> {"compiles", "trips", "last"}
_RETRACE = {}
# transfer watchdog: hot-loop span names already warned about
_D2H_WARNED = set()
_D2H_WARMUP = 2           # first occurrences of a span may legitimately sync


class _D2HLocal(threading.local):
    """Per-thread d2h sync count. Span attribution reads THIS, not the
    global counter: a span times a host region on its own thread, so a
    concurrent server thread's ``asnumpy`` (the serving fetch path) must
    not land in another thread's ``<name>.d2h`` delta. The global
    ``transfer.d2h`` counter still aggregates every thread."""

    def __init__(self):
        self.count = 0


_D2H_LOCAL = _D2HLocal()

# JSONL sink: hot path appends to the queue; a flush (explicit, atexit, or
# the off-thread timer) drains it to the file
_SINK = {"queue": collections.deque(maxlen=1 << 20), "thread": None,
         "atexit": False, "lock": threading.Lock()}

# extension points (mxtpu/fleet_obs.py rides both): flush hooks run after
# every sink flush — periodic, explicit, AND the atexit/SIGTERM final one;
# prometheus extras append provider output to the /metrics exposition
_FLUSH_HOOKS = []
_PROM_EXTRAS = []

# ---- causal tracing state ----
# current trace context (None outside any trace); contextvars are
# per-thread by construction, which is exactly the handoff discipline:
# a trace crosses a thread boundary ONLY through trace_handoff()
_TRACE_CV = contextvars.ContextVar("mxtpu_trace", default=None)
_SPAN_IDS = itertools.count(1)   # process-global span ids (GIL-atomic)
_TRACE_IDS = itertools.count(1)
_TRACE_PREFIX = "%04x" % (os.getpid() & 0xFFFF)


def _trace_ring_cap():
    try:
        return int(os.environ.get("MXTPU_TRACE_RING", "4096"))
    except ValueError:
        return 4096


# flight-recorder ring: (kind, trace_id, span_id, parent, name, ts_us,
# dur_us, tid) tuples; parent is a span id for kind=="span", a
# (trace_id, span_id) source pair for kind=="link"
_TRACE_EVENTS = collections.deque(maxlen=_trace_ring_cap())
# consumer -> next-trace link handoffs (data.wait / data.h2d): the step
# trace that CONSUMES a batch drains these into link events. THREAD-LOCAL:
# both pend (loader __next__) and drain (Trainer.step) happen on the
# consuming thread, and a process-global queue would let a background
# thread's loader events misattribute to the foreground thread's step
class _PendingLocal(threading.local):
    def __init__(self):
        self.q = collections.deque(maxlen=64)


_PENDING_LINKS = _PendingLocal()
_FLIGHT = {"count": 0, "lock": threading.Lock()}


# ------------------------------------------------------------------ policies
def enabled():
    """Span/event/sink machinery lever: ``MXTPU_TELEMETRY`` default ON
    (read per call, like every other A/B lever, so bench can flip it
    mid-process). ``0`` disables spans; bare counters stay always-on."""
    return os.environ.get("MXTPU_TELEMETRY", "1") != "0"


def jsonl_path():
    """``MXTPU_TELEMETRY`` doubles as the sink switch: any value other
    than ``0``/``1`` is a JSONL path observations stream to."""
    v = os.environ.get("MXTPU_TELEMETRY", "1")
    return v if v not in ("0", "1") else None


def tracing_enabled():
    """Causal-tracing lever: ``MXTPU_TRACE`` default ON (requires the
    span machinery, so ``MXTPU_TELEMETRY=0`` implies off). Tracing is
    pure host bookkeeping — an id allocation, a contextvar set, and a
    bounded ring append per span — so the zero-host-sync and
    ``trainer.step.d2h == 0`` contracts hold with it ON (pinned by the
    transfer-guard test parametrized over this var)."""
    return os.environ.get("MXTPU_TRACE", "1") != "0" and enabled()


def flight_dir():
    """Flight-recorder artifact directory (``MXTPU_FLIGHT_DIR``). Unset
    or empty = no files are written (the in-memory ring and
    :func:`flight_snapshot` still work); triggers call
    :func:`flight_record` unconditionally and it no-ops here."""
    return os.environ.get("MXTPU_FLIGHT_DIR") or None


def flight_max():
    """Dump cap per process (``MXTPU_FLIGHT_MAX``, default 16): a
    repeatedly-tripping watchdog must not fill the disk with thousands
    of near-identical artifacts."""
    try:
        return int(os.environ.get("MXTPU_FLIGHT_MAX", "16"))
    except ValueError:
        return 16


def retrace_budget():
    """Compiles a single jit-cache site may accumulate before the retrace
    watchdog warns (``MXTPU_RETRACE_BUDGET``, default 64 — far above any
    legitimate warmup, low enough to catch a per-step recompile within
    the first minute)."""
    return int(os.environ.get("MXTPU_RETRACE_BUDGET", "64"))


def _flush_interval():
    """Off-thread flush period in seconds (``MXTPU_TELEMETRY_FLUSH_S``);
    0 (default) = no background thread — flush happens on
    :func:`flush` and at interpreter exit."""
    try:
        return float(os.environ.get("MXTPU_TELEMETRY_FLUSH_S", "0"))
    except ValueError:
        return 0.0


# ----------------------------------------------------------------- registry
def inc(name, n=1, tag=None):
    """Add ``n`` to a counter. ``tag`` keys a labeled sub-counter (e.g.
    pallas fallback reasons). Always-on: a single locked dict update."""
    k = (name, tag)
    with _LOCK:
        _COUNTERS[k] = _COUNTERS.get(k, 0) + n


def gauge(name, v, tag=None):
    """Set a gauge to the latest value (last-write-wins). ``tag`` keys a
    labeled sub-gauge (e.g. the per-device ``memory.hbm_*_bytes{device}``
    family) exactly like counter tags."""
    with _LOCK:
        _GAUGES[(name, tag)] = float(v)


def observe(name, v):
    """Record one histogram observation (span durations land here)."""
    v = float(v)
    with _LOCK:
        h = _HISTS.get(name)
        if h is None:
            h = [0, 0.0, v, v, collections.deque(maxlen=_RESERVOIR)]
            _HISTS[name] = h
        h[0] += 1
        h[1] += v
        h[2] = min(h[2], v)
        h[3] = max(h[3], v)
        h[4].append(v)
    p = jsonl_path()
    if p is not None:
        _queue_line({"t": time.time(), "kind": "obs", "metric": name,
                     "value": v}, p)


def value(name, tag=None):
    """Current counter value (0 when never incremented); with no ``tag``
    and no untagged entry, the sum across tags."""
    with _LOCK:
        v = _COUNTERS.get((name, tag))
        if v is not None or tag is not None:
            return v or 0
        return sum(v for (n, t), v in _COUNTERS.items()
                   if n == name and t is not None) or 0


def tagged(name):
    """``{tag: value}`` over a labeled counter family."""
    with _LOCK:
        return {t: v for (n, t), v in _COUNTERS.items()
                if n == name and t is not None}


def gauge_value(name, tag=None):
    """Current gauge value, or None when never set (gauges are
    last-write-wins, so unlike :func:`value` there is no meaningful
    zero default or cross-tag sum)."""
    with _LOCK:
        return _GAUGES.get((name, tag))


def reset_metric(name):
    """Zero one metric (counters incl. tags, gauge, histogram) — the
    adopted stats views (``reset_dispatch_stats``) use this; it must NOT
    clear the rest of the registry."""
    with _LOCK:
        for k in [k for k in _COUNTERS if k[0] == name]:
            del _COUNTERS[k]
        for k in [k for k in _GAUGES if k[0] == name]:
            del _GAUGES[k]
        _HISTS.pop(name, None)


def _quantile(sorted_vals, q):
    n = len(sorted_vals)
    if n == 0:
        return None
    pos = q * (n - 1)
    lo = int(pos)
    hi = min(lo + 1, n - 1)
    frac = pos - lo
    return sorted_vals[lo] * (1 - frac) + sorted_vals[hi] * frac


def snapshot():
    """Structured aggregate view of everything the registry holds."""
    with _LOCK:
        by_name = {}
        for (name, tag), v in _COUNTERS.items():
            by_name.setdefault(name, {})[tag] = v
        # pure-untagged collapses to a scalar; a name incremented BOTH
        # ways keeps every entry (untagged under "_untagged") — mixing
        # must not silently drop either form from the aggregate view
        counters = {}
        for name, tags in by_name.items():
            if set(tags) == {None}:
                counters[name] = tags[None]
            else:
                counters[name] = {
                    ("_untagged" if t is None else t): v
                    for t, v in tags.items()}
        g_by_name = {}
        for (name, tag), v in _GAUGES.items():
            g_by_name.setdefault(name, {})[tag] = v
        # same collapse rule as counters: pure-untagged gauges stay
        # scalars (every pre-existing consumer reads them that way),
        # tagged families become {tag: value} dicts
        gauges = {}
        for name, tags in g_by_name.items():
            if set(tags) == {None}:
                gauges[name] = tags[None]
            else:
                gauges[name] = {("_untagged" if t is None else t): v
                                for t, v in tags.items()}
        hists = {}
        for name, (cnt, total, mn, mx, res) in _HISTS.items():
            vals = sorted(res)
            hists[name] = {"count": cnt, "sum": total, "mean": total / cnt,
                           "min": mn, "max": mx,
                           "p50": _quantile(vals, 0.5),
                           "p99": _quantile(vals, 0.99)}
        retrace = {site: dict(st) for site, st in _RETRACE.items()}
    snap = {"counters": counters, "gauges": gauges, "histograms": hists,
            "retrace": retrace}
    # executable-ledger export (mxtpu/xprof.py): the resolve-free view —
    # a /metrics scrape must never invoke the compiler
    from . import xprof
    if xprof.enabled():
        led = xprof.ledger_snapshot()
        if led:
            snap["ledger"] = led
    return snap


def report():
    """The aggregate table, profiler-dumps style: one call shows guard
    activity, dispatch routing, retries, and the step-phase timing without
    a log scrape."""
    snap = snapshot()
    lines = []
    if snap["histograms"]:
        lines.append("%-38s %8s %10s %10s %10s %10s" %
                     ("Span/Histogram", "Count", "Mean(ms)", "P50(ms)",
                      "P99(ms)", "Max(ms)"))
        for name in sorted(snap["histograms"],
                           key=lambda n: -snap["histograms"][n]["sum"]):
            h = snap["histograms"][name]
            lines.append("%-38s %8d %10.3f %10.3f %10.3f %10.3f" %
                         (name, h["count"], h["mean"] * 1e3,
                          (h["p50"] or 0) * 1e3, (h["p99"] or 0) * 1e3,
                          h["max"] * 1e3))
    if snap["counters"]:
        lines.append("")
        lines.append("%-38s %12s" % ("Counter", "Value"))
        for name in sorted(snap["counters"]):
            v = snap["counters"][name]
            if isinstance(v, dict):
                for tag in sorted(v):
                    lines.append("%-38s %12g" %
                                 ("%s{%s}" % (name, tag), v[tag]))
            else:
                lines.append("%-38s %12g" % (name, v))
    if snap["gauges"]:
        lines.append("")
        lines.append("%-38s %12s" % ("Gauge", "Value"))
        for name in sorted(snap["gauges"]):
            v = snap["gauges"][name]
            if isinstance(v, dict):
                for tag in sorted(v):
                    lines.append("%-38s %12g" %
                                 ("%s{%s}" % (name, tag), v[tag]))
            else:
                lines.append("%-38s %12g" % (name, v))
    if snap["retrace"]:
        lines.append("")
        lines.append("%-20s %9s %6s  %s" %
                     ("Retrace site", "Compiles", "Trips", "Last provenance"))
        for site in sorted(snap["retrace"]):
            st = snap["retrace"][site]
            lines.append("%-20s %9d %6d  %s" %
                         (site, st["compiles"], st["trips"],
                          st["last"]))
    return "\n".join(lines) if lines else "(telemetry registry empty)"


def events():
    """The bounded span-event ring — (name, cat, ts_us, dur_us, tid)
    tuples on the ``time.perf_counter_ns`` clock, the SAME clock and
    shape :mod:`mxtpu.profiler` records op events with, so
    ``profiler.dump()`` merges them into one chrome trace."""
    with _LOCK:
        return list(_EVENTS)


def reset():
    """Test hook: clear the whole registry, event ring, trace ring, and
    watchdog state (the sink file, if any, is left alone). The trace
    ring is re-created so a changed ``MXTPU_TRACE_RING`` takes effect."""
    global _TRACE_EVENTS
    with _LOCK:
        _COUNTERS.clear()
        _GAUGES.clear()
        _HISTS.clear()
        _EVENTS.clear()
        _RETRACE.clear()
        _D2H_WARNED.clear()
        _TRACE_EVENTS = collections.deque(maxlen=_trace_ring_cap())
        _PENDING_LINKS.q.clear()  # the calling thread's (tests drain
        _FLIGHT["count"] = 0      # their own; other threads' are bounded)
    del _FLUSH_HOOKS[:]
    del _PROM_EXTRAS[:]
    from . import xprof
    xprof.reset()  # the executable ledger rides the registry lifecycle


# -------------------------------------------------------------------- spans
class span:
    """Context manager timing a host-side region into the histogram
    ``name`` (seconds) and the chrome-trace event ring. ``d2h=True``
    additionally attributes device->host syncs observed inside the region
    to ``<name>.d2h`` and arms the transfer watchdog: a steady-state
    occurrence (past the first ``_D2H_WARMUP``) that syncs at all warns
    once — the guarded hot loop's contract is ZERO.

    Causal tracing: when a :class:`TraceContext` is active on this
    thread (see :func:`new_trace` / :func:`trace_handoff`) the span joins
    the trace tree — it allocates a span id, becomes the current context
    for its body (children nest under it), and records one trace-ring
    event with its parent linkage on exit. ``new_trace=True`` starts a
    fresh trace when none is active (the per-request / per-step roots);
    with one already active it simply nests, preserving causality.

    Pure host bookkeeping: no device ops, no syncs — safe under a
    ``jax.transfer_guard`` and inside the zero-sync Trainer.step contract.
    The enter/exit pair is hand-tuned for sub-millisecond hot loops: ONE
    env read (lever + sink path resolved together), ONE lock acquisition
    on exit (histogram + event ring inline), lock-free d2h snapshot.
    """

    __slots__ = ("name", "cat", "_d2h", "_t0", "_d0", "_sink",
                 "_new_trace", "_parent", "_tok", "ctx")

    def __init__(self, name, cat="phase", d2h=False, new_trace=False):
        self.name = name
        self.cat = cat
        self._d2h = d2h
        self._new_trace = new_trace
        self._t0 = None
        self._d0 = None
        self._sink = None
        self._parent = None
        self._tok = None
        self.ctx = None

    def __enter__(self):
        lever = os.environ.get("MXTPU_TELEMETRY", "1")
        if lever != "0":
            self._sink = lever if lever != "1" else None
            parent = _TRACE_CV.get()
            if parent is None and self._new_trace \
                    and os.environ.get("MXTPU_TRACE", "1") != "0":
                parent = new_trace()
            if parent is not None:
                self._parent = parent.span_id
                self.ctx = TraceContext(parent.trace_id, next(_SPAN_IDS),
                                        parent._stages)
                self._tok = _TRACE_CV.set(self.ctx)
            self._t0 = time.perf_counter_ns()
            if self._d2h:
                # thread-local snapshot: only syncs issued by THIS thread
                # inside the region are attributed — concurrent server
                # threads cannot corrupt another span's delta
                self._d0 = _D2H_LOCAL.count
        return self

    def __exit__(self, *exc):
        t0 = self._t0
        if t0 is None:
            return False
        dur_ns = time.perf_counter_ns() - t0
        v = dur_ns * 1e-9
        name = self.name
        if self._tok is not None:
            _TRACE_CV.reset(self._tok)
            self._tok = None
            _TRACE_EVENTS.append(
                ("span", self.ctx.trace_id, self.ctx.span_id, self._parent,
                 name, t0 // 1000, dur_ns // 1000,
                 threading.get_ident() & 0xFFFF))
        with _LOCK:
            h = _HISTS.get(name)
            if h is None:
                h = [0, 0.0, v, v, collections.deque(maxlen=_RESERVOIR)]
                _HISTS[name] = h
            h[0] += 1
            h[1] += v
            if v < h[2]:
                h[2] = v
            if v > h[3]:
                h[3] = v
            h[4].append(v)
            occurrences = h[0]
            _EVENTS.append((name, self.cat, t0 // 1000, dur_ns // 1000,
                            threading.get_ident() & 0xFFFF))
        if self._sink is not None:
            rec = {"t": time.time(), "kind": "obs", "metric": name,
                   "value": v}
            if self.ctx is not None:
                # trace linkage rides the SAME obs line (old readers
                # ignore the extra keys): tools/telemetry_report.py
                # rebuilds per-trace critical paths from these
                rec["trace"] = self.ctx.trace_id
                rec["span"] = self.ctx.span_id
                rec["parent"] = self._parent
            _queue_line(rec, self._sink)
        if self._d0 is not None:
            delta = _D2H_LOCAL.count - self._d0
            if delta:
                inc(name + ".d2h", delta)
                self._watchdog(delta, occurrences)
        self._t0 = None
        return False

    def _watchdog(self, delta, occurrences):
        with _LOCK:
            if occurrences <= _D2H_WARMUP or self.name in _D2H_WARNED:
                return
            _D2H_WARNED.add(self.name)
        _log.warning(
            "transfer watchdog: %d device->host sync(s) inside '%s' after "
            "warmup (occurrence %d) — the hot loop should be transfer-free; "
            "fetch verdicts/metrics asynchronously off the step path "
            "(docs/observability.md)", delta, self.name, occurrences)


# ----------------------------------------------------------- causal tracing
class TraceContext:
    """One position in a trace tree: ``trace_id`` (process-prefixed hex
    string) + ``span_id`` (globally unique int; 0 = the trace root).
    Contexts are immutable hand-off tokens: :class:`span` derives a child
    for its body, :func:`trace_handoff` adopts one on another thread.
    ``_stages`` is the per-TRACE accumulator shared by every context of
    the trace — :func:`add_stage` appends (stage, seconds) pairs there
    and :func:`trace_breakdown` folds them into the latency breakdown a
    served request returns."""

    __slots__ = ("trace_id", "span_id", "_stages")

    def __init__(self, trace_id, span_id, stages):
        self.trace_id = trace_id
        self.span_id = span_id
        self._stages = stages

    def __repr__(self):
        return "TraceContext(%s, span=%d)" % (self.trace_id, self.span_id)


def new_trace():
    """Root context for a fresh trace (None when tracing is off). The
    per-request / per-step entry points call this; everything below them
    nests via :class:`span` or joins via :func:`trace_handoff`."""
    if not tracing_enabled():
        return None
    return TraceContext("%s-%x" % (_TRACE_PREFIX, next(_TRACE_IDS)), 0, [])


def current_trace():
    """This thread's active context (None outside any trace)."""
    return _TRACE_CV.get()


class trace_handoff:
    """Adopt ``ctx`` as the current trace for a ``with`` body — THE way a
    trace crosses a thread boundary (contextvars do not follow threads,
    by design: implicit inheritance would attribute a worker's whole
    lifetime to whichever request was live when it spawned). ``ctx`` may
    be None (tracing off / untraced caller): the handoff is a no-op, so
    call sites stay unconditional."""

    __slots__ = ("_ctx", "_tok")

    def __init__(self, ctx):
        self._ctx = ctx
        self._tok = None

    def __enter__(self):
        if self._ctx is not None:
            self._tok = _TRACE_CV.set(self._ctx)
        return self._ctx

    def __exit__(self, *exc):
        if self._tok is not None:
            _TRACE_CV.reset(self._tok)
            self._tok = None
        return False


def add_stage(ctx, name, dur_s, event=False):
    """Credit ``dur_s`` seconds of stage ``name`` to ``ctx``'s trace
    breakdown (None-safe). ``event=True`` additionally records a trace
    event under ``ctx`` — used for stages measured OUTSIDE a span body
    (queue-wait is an interval between threads, not a code region).
    Batch-level stages (pad/predict/fetch) are credited to every cohort
    member's breakdown but recorded as ONE event under the lead trace:
    each request's numbers stay per-request, the tree stays deduplicated."""
    if ctx is None:
        return
    ctx._stages.append((name, float(dur_s)))
    if event:
        now_us = time.perf_counter_ns() // 1000
        dur_us = int(dur_s * 1e6)
        sid = next(_SPAN_IDS)
        _TRACE_EVENTS.append(
            ("span", ctx.trace_id, sid, ctx.span_id, name,
             max(0, now_us - dur_us), dur_us,
             threading.get_ident() & 0xFFFF))
        p = jsonl_path()
        if p is not None:
            # interval stages reach the sink like span observations do,
            # so the per-trace critical path (telemetry_report --traces)
            # sees queue-wait next to the span stages
            _queue_line({"t": time.time(), "kind": "obs", "metric": name,
                         "value": float(dur_s), "trace": ctx.trace_id,
                         "span": sid, "parent": ctx.span_id}, p)


def trace_mark(ctx, name):
    """Zero-duration marker event in ``ctx``'s trace (None-safe) — e.g.
    ``serving.redispatch`` when a wedged batch re-enters the queue."""
    if ctx is None:
        return
    _TRACE_EVENTS.append(
        ("mark", ctx.trace_id, next(_SPAN_IDS), ctx.span_id, name,
         time.perf_counter_ns() // 1000, 0,
         threading.get_ident() & 0xFFFF))


def link(src, name="link"):
    """Causal edge from ``src`` (a TraceContext on ANOTHER trace/thread)
    to the CURRENT context — rendered as a chrome-trace flow arrow by
    ``profiler.dump()``. No-op when either side is absent."""
    dst = _TRACE_CV.get()
    if src is None or dst is None:
        return
    _TRACE_EVENTS.append(
        ("link", dst.trace_id, dst.span_id, (src.trace_id, src.span_id),
         name, time.perf_counter_ns() // 1000, 0,
         threading.get_ident() & 0xFFFF))


def pend_link(name, ctx):
    """Queue a causal edge whose DESTINATION does not exist yet: the
    loader's ``__next__`` (on the CONSUMING thread) records the batch's
    ``data.h2d``/``data.wait`` contexts here, and the next
    ``trainer.step`` trace ON THE SAME THREAD drains them via
    :func:`link_pending` — the step that consumes a batch links the
    transfer that produced it. The queue is thread-local, so a
    background thread's loader can never pollute another thread's step;
    within one thread, iteration that never reaches a step (e.g. an
    interleaved un-stepped validation pass) attributes to the NEXT step
    drained there — the bounded queue caps how far that can drift."""
    if ctx is not None:
        _PENDING_LINKS.q.append((name, ctx.trace_id, ctx.span_id))


def link_pending():
    """Drain this thread's pended edges into link events targeting the
    current context. Returns the number of links emitted (0 outside a
    trace — the queue is cleared either way so stale edges never attach
    to an unrelated later step)."""
    dst = _TRACE_CV.get()
    q = _PENDING_LINKS.q
    n = 0
    while True:
        try:
            name, src_trace, src_span = q.popleft()
        except IndexError:
            break
        if dst is None:
            continue
        _TRACE_EVENTS.append(
            ("link", dst.trace_id, dst.span_id, (src_trace, src_span),
             name, time.perf_counter_ns() // 1000, 0,
             threading.get_ident() & 0xFFFF))
        n += 1
    return n


def trace_breakdown(ctx):
    """Fold ``ctx``'s stage accumulator into ``{stage: seconds}`` (empty
    when untraced). The serving path returns this per request; its values
    sum to ~the request's end-to-end latency (serve_bench's 5% gate)."""
    if ctx is None:
        return {}
    out = {}
    for name, dur in list(ctx._stages):
        out[name] = out.get(name, 0.0) + dur
    return out


def trace_events(trace_id=None):
    """Snapshot of the trace ring as dicts (optionally one trace's);
    ``parent`` is a span id for tree edges, ``{"trace", "span"}`` for
    cross-trace links."""
    out = []
    for kind, tr, sp, parent, name, ts, dur, tid in list(_TRACE_EVENTS):
        if trace_id is not None and tr != trace_id:
            continue
        rec = {"kind": kind, "trace": tr, "span": sp, "name": name,
               "ts_us": ts, "dur_us": dur, "tid": tid}
        if kind == "link":
            rec["parent"] = {"trace": parent[0], "span": parent[1]}
        else:
            rec["parent"] = parent
        out.append(rec)
    return out


def trace_flows(lo=None, hi=None):
    """Chrome-trace flow events (``ph: s/f`` pairs) for the trace ring's
    causal edges — parent→child span edges (cat ``trace``, flow id = the
    globally-unique child span id) and explicit cross-thread links (cat
    ``trace.link``, a fresh id per link: several links may target the
    SAME destination span, e.g. every cohort member linking the lead) —
    scoped to a ``[lo, hi]`` ts window like the rest of
    ``profiler.dump()``'s merge. A link whose source is a trace ROOT
    (span 0 — roots have no ring event of their own) anchors to that
    trace's earliest recorded event instead of being dropped."""
    evs = list(_TRACE_EVENTS)
    index = {}
    first_of_trace = {}
    for kind, tr, sp, parent, name, ts, dur, tid in evs:
        if kind != "link":
            index[(tr, sp)] = (ts, dur, tid)
            best = first_of_trace.get(tr)
            if best is None or ts < best[0]:
                first_of_trace[tr] = (ts, dur, tid)
    flows = []

    def _in_window(ts):
        return (lo is None or ts >= lo) and (hi is None or ts <= hi)

    for i, (kind, tr, sp, parent, name, ts, dur, tid) in enumerate(evs):
        if kind == "link":
            src = index.get(parent)
            if src is None and parent[1] == 0:
                src = first_of_trace.get(parent[0])
            if src is None or not _in_window(ts):
                continue
            s_ts, s_dur, s_tid = src
            link_id = (1 << 32) + i  # disjoint from span-id flow ids
            flows.append({"ph": "s", "cat": "trace.link", "name": name,
                          "id": link_id, "ts": s_ts + s_dur, "pid": 0,
                          "tid": s_tid})
            flows.append({"ph": "f", "bp": "e", "cat": "trace.link",
                          "name": name, "id": link_id, "ts": ts, "pid": 0,
                          "tid": tid})
        elif kind == "span" and parent:
            src = index.get((tr, parent))
            if src is None or not _in_window(ts):
                continue
            s_ts, _s_dur, s_tid = src
            # the parent span's X event starts at s_ts; arrow from the
            # parent's start to the child's start shows the causal tree
            # even when the child ran on another thread
            flows.append({"ph": "s", "cat": "trace", "name": name,
                          "id": sp, "ts": s_ts, "pid": 0, "tid": s_tid})
            flows.append({"ph": "f", "bp": "e", "cat": "trace",
                          "name": name, "id": sp, "ts": ts, "pid": 0,
                          "tid": tid})
    return flows


# ---------------------------------------------------------- flight recorder
def flight_snapshot(reason, trace_ids=(), extra=None):
    """The post-mortem dict: recent trace events, per-thread stacks, the
    registry snapshot, and the owning trace ids the trigger tagged
    (wedge/breaker/fault sites pass the affected requests' traces)."""
    import sys
    import traceback
    names = {t.ident: t.name for t in threading.enumerate()}
    stacks = []
    for tid, frame in sys._current_frames().items():
        stacks.append({"thread_id": tid,
                       "thread_name": names.get(tid, "?"),
                       "stack": traceback.format_stack(frame)})
    snap = {"reason": reason, "t": time.time(), "pid": os.getpid(),
            "trace_ids": list(trace_ids),
            "events": trace_events(),
            "threads": stacks,
            "registry": snapshot()}
    if extra:
        snap["extra"] = dict(extra)
    return snap


def flight_record(reason, trace_ids=(), extra=None):
    """Dump a :func:`flight_snapshot` JSON artifact to
    ``MXTPU_FLIGHT_DIR`` (no-op returning None when unset). Triggers:
    wedge-watchdog trips, circuit-breaker opens, retrace-watchdog first
    trips, injected faults, serving worker crashes, and SIGTERM. Bounded
    by ``MXTPU_FLIGHT_MAX`` dumps per process; the write is tmp+rename so
    a dump interrupted by the dying process never leaves a torn artifact."""
    d = flight_dir()
    if d is None:
        return None
    with _FLIGHT["lock"]:
        if _FLIGHT["count"] >= flight_max():
            return None
        _FLIGHT["count"] += 1
        seq = _FLIGHT["count"]
    try:
        os.makedirs(d, exist_ok=True)
        safe = "".join(c if c.isalnum() or c in "-_" else "_"
                       for c in str(reason))
        path = os.path.join(d, "flight_%s_%d_%d.json"
                            % (safe, os.getpid(), seq))
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(flight_snapshot(reason, trace_ids, extra), f)
        os.replace(tmp, path)
    except OSError as e:  # pragma: no cover - dump IO failure
        _log.warning("flight recorder dump failed: %s", e)
        return None
    inc("flight.dumps", tag=str(reason))
    _log.warning("flight recorder: dumped %s (reason=%s, traces=%s)",
                 path, reason, list(trace_ids) or "-")
    return path


# ------------------------------------------------------ prometheus rendering
def _prom_name(name):
    out = []
    for ch in name:
        out.append(ch if ch.isalnum() or ch in "_:" else "_")
    return "mxtpu_" + "".join(out)


def _prom_label(v):
    return str(v).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def prometheus():
    """The whole registry in Prometheus text exposition format 0.0.4:
    counters (tag families as a ``tag`` label), gauges, and histograms as
    summaries (``quantile`` 0.5/0.99 + ``_sum``/``_count``). The model
    server serves this on ``/metrics`` under ``Accept: text/plain`` so a
    stock Prometheus scraper needs no sidecar. Registered extras (e.g. a
    FleetObservatory's host-labeled fleet view) run FIRST — a provider
    that refreshes registry gauges lands them in this same scrape — and
    their output is appended after the registry's own families."""
    extras = []
    for fn in list(_PROM_EXTRAS):
        try:
            out = fn()
        except Exception as e:  # noqa: BLE001 — a broken provider must
            _log.warning("prometheus extra %r failed: %s", fn, e)
            continue           # not take down the scrape
        if out:
            extras.append(out.rstrip("\n"))
    snap = snapshot()
    lines = []
    for name in sorted(snap["counters"]):
        v = snap["counters"][name]
        pn = _prom_name(name)
        lines.append("# TYPE %s counter" % pn)
        if isinstance(v, dict):
            for tag in sorted(v):
                if tag == "_untagged":
                    lines.append("%s %g" % (pn, v[tag]))
                else:
                    lines.append('%s{tag="%s"} %g'
                                 % (pn, _prom_label(tag), v[tag]))
        else:
            lines.append("%s %g" % (pn, v))
    for name in sorted(snap["gauges"]):
        v = snap["gauges"][name]
        pn = _prom_name(name)
        lines.append("# TYPE %s gauge" % pn)
        if isinstance(v, dict):
            for tag in sorted(v):
                if tag == "_untagged":
                    lines.append("%s %g" % (pn, v[tag]))
                else:
                    lines.append('%s{tag="%s"} %g'
                                 % (pn, _prom_label(tag), v[tag]))
        else:
            lines.append("%s %g" % (pn, v))
    for name in sorted(snap["histograms"]):
        h = snap["histograms"][name]
        pn = _prom_name(name)
        lines.append("# TYPE %s summary" % pn)
        if h["p50"] is not None:
            lines.append('%s{quantile="0.5"} %g' % (pn, h["p50"]))
        if h["p99"] is not None:
            lines.append('%s{quantile="0.99"} %g' % (pn, h["p99"]))
        lines.append("%s_sum %g" % (pn, h["sum"]))
        lines.append("%s_count %d" % (pn, h["count"]))
    lines.extend(extras)
    return "\n".join(lines) + "\n"


def register_prometheus_extra(fn):
    """Register a zero-arg provider whose text-exposition output is
    appended to every :func:`prometheus` render (idempotent; cleared by
    :func:`reset`). Returns ``fn``."""
    if fn not in _PROM_EXTRAS:
        _PROM_EXTRAS.append(fn)
    return fn


# -------------------------------------------------------- transfer watchdog
def record_d2h(n=1):
    """Called from the NDArray sync points (``asnumpy`` and friends): one
    global device->host sync counter, always on, plus a thread-local count
    — spans opened with ``d2h=True`` attribute the THREAD-LOCAL delta to
    their region, so concurrent server threads (``mxtpu.serving``) cannot
    pollute the hot loop's per-region attribution."""
    inc("transfer.d2h", n)
    _D2H_LOCAL.count += n


def d2h_count():
    return value("transfer.d2h")


# --------------------------------------------------------- retrace watchdog
def record_retrace(site, provenance=None, compiled=None, compile_s=None):
    """Report one jit-cache compile at ``site`` with its cache-key
    provenance (optimizer class, ``registry.policy_key`` tuple, ...).
    Counts into ``retrace.<site>``; past :func:`retrace_budget` compiles
    the watchdog warns with the provenance and bumps
    ``retrace.watchdog_trips`` — a steady-state recompile means a policy
    env flipped mid-run or a cache key is unstable (shapes/hyper leaking
    into the static config), both of which silently serialize training
    behind the compiler.

    ``compiled=`` (ISSUE 12) hands the freshly-built executable to the
    :mod:`mxtpu.xprof` ledger: pass the jitted callable and CACHE THE
    RETURN VALUE — with the observatory on it comes back wrapped for
    first-dispatch compile timing, call counting, and lazy
    cost/memory-analysis capture (``MXTPU_XPROF=0`` returns it
    unchanged). Without ``compiled`` the call behaves exactly as before
    and returns None.

    ``compile_s=`` (the compile service's AOT path) carries an
    explicitly-measured lower+compile wall time: the executable arrives
    already compiled, so the wrapper must not re-time the first
    dispatch."""
    inc("retrace." + site)
    wrapped = None
    if compiled is not None:
        from . import xprof
        wrapped = xprof.attach(site, provenance, compiled,
                               compile_s=compile_s)
    budget = retrace_budget()
    with _LOCK:
        st = _RETRACE.setdefault(site,
                                 {"compiles": 0, "trips": 0, "last": None})
        st["compiles"] += 1
        st["last"] = provenance
        over = st["compiles"] > budget
        if over:
            st["trips"] += 1
        compiles = st["compiles"]
        trips = st["trips"]
    if over:
        inc("retrace.watchdog_trips")
        if trips == 1:
            # first trip at this site: capture the moment (the provenance
            # of the compile that blew the budget + who is on-stack)
            flight_record("retrace_watchdog",
                          extra={"site": site, "compiles": compiles,
                                 "provenance": str(provenance)})
        # rate-limit the LOG (the trip counter stays exact): the target
        # pathology is a recompile every step — warning each time would
        # flood hours of logs with the message meant to make them readable
        if trips != 1 and trips % 100 != 0:
            return wrapped
        _log.warning(
            "retrace watchdog: '%s' compiled %d times, over "
            "MXTPU_RETRACE_BUDGET=%d. Last provenance: %s. Steady-state "
            "recompiles usually mean a policy env var flipped mid-run or "
            "an unstable cache key — each one stalls every step behind "
            "the compiler (docs/observability.md)",
            site, compiles, budget, provenance)
    return wrapped


def retrace_stats(site=None):
    """Watchdog state: ``{site: {compiles, trips, last}}`` (or one
    site's dict / None)."""
    with _LOCK:
        if site is not None:
            st = _RETRACE.get(site)
            return dict(st) if st else None
        return {s: dict(st) for s, st in _RETRACE.items()}


# --------------------------------------------------------------- JSONL sink
def _queue_line(rec, path):
    _SINK["queue"].append((path, rec))
    interval = _flush_interval()
    if interval > 0 and _SINK["thread"] is None:
        with _SINK["lock"]:
            if _SINK["thread"] is None:
                t = threading.Thread(target=_flush_loop, args=(interval,),
                                     daemon=True, name="mxtpu-telemetry")
                _SINK["thread"] = t
                t.start()


def _flush_loop(interval):
    while True:
        time.sleep(interval)
        try:
            flush()
        except Exception:  # noqa: BLE001 — a sink error must never kill
            pass           # the flusher (next interval retries)


def flush():
    """Drain queued observations to the JSONL sink and append one
    cumulative line per counter/gauge. Off the hot path by construction
    (explicit call, atexit, or the off-thread timer)."""
    path = jsonl_path()
    lines_by_path = {}
    while True:
        try:
            p, rec = _SINK["queue"].popleft()
        except IndexError:
            break
        lines_by_path.setdefault(p, []).append(rec)
    if path is not None:
        now = time.time()
        with _LOCK:
            for (name, tag), v in _COUNTERS.items():
                rec = {"t": now, "kind": "counter", "metric": name,
                       "value": v}
                if tag is not None:
                    rec["tag"] = tag
                lines_by_path.setdefault(path, []).append(rec)
            for (name, tag), v in _GAUGES.items():
                rec = {"t": now, "kind": "gauge", "metric": name,
                       "value": v}
                if tag is not None:
                    rec["tag"] = tag
                lines_by_path.setdefault(path, []).append(rec)
        # executable-ledger lines (kind="ledger", cumulative like the
        # counters — tools/telemetry_report.py --ledger folds the last
        # line per (site, seq) into the roofline table). Resolve-free:
        # flush may run at interpreter exit, no compiler invocations.
        from . import xprof
        if xprof.enabled():
            for e in xprof.ledger_snapshot():
                lines_by_path.setdefault(path, []).append(
                    dict(e, t=now, kind="ledger"))
    with _SINK["lock"]:
        for p, recs in lines_by_path.items():
            try:
                with open(p, "a") as f:
                    for rec in recs:
                        f.write(json.dumps(rec) + "\n")
            except OSError as e:  # pragma: no cover - sink IO failure
                _log.warning("telemetry sink write to %s failed: %s", p, e)
    for fn in list(_FLUSH_HOOKS):
        try:
            fn()
        except Exception as e:  # noqa: BLE001 — a broken hook must not
            _log.warning("flush hook %r failed: %s", fn, e)  # kill a flush


def on_flush(fn):
    """Register a zero-arg hook to run after every :func:`flush` —
    including the atexit/SIGTERM final one, which is how the fleet obs
    blob (mxtpu/fleet_obs.py) captures a dying host's last window.
    Idempotent; cleared by :func:`reset`. Returns ``fn``."""
    if fn not in _FLUSH_HOOKS:
        _FLUSH_HOOKS.append(fn)
    return fn


# Final-flush guarantee (ISSUE 19 satellite): registration used to be
# lazy inside _queue_line, so a process that only bumped counters (never
# queued an obs line) lost its cumulative counter/gauge lines even on a
# CLEAN exit — and the off-thread timer is a daemon, so exit-between-
# flushes lost the last window too. Register unconditionally at import:
# flush() with no sink configured is a cheap no-op.
import atexit  # noqa: E402  (deliberate: after flush is defined)

atexit.register(flush)
_SINK["atexit"] = True
