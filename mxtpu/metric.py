"""Evaluation metrics (ref: python/mxnet/metric.py:68-1312 — registry + Accuracy,
TopK, F1, MCC, Perplexity, MAE/MSE/RMSE, CrossEntropy, NLL, PearsonCorrelation,
Loss, CustomMetric, CompositeEvalMetric)."""
from __future__ import annotations

import math

import numpy as _np

from .base import MXNetError
from .ndarray import NDArray

_REGISTRY = {}


def register(klass):
    _REGISTRY[klass.__name__.lower()] = klass
    return klass


def _as_np(x):
    return x.asnumpy() if isinstance(x, NDArray) else _np.asarray(x)


def check_label_shapes(labels, preds, shape=False):
    if len(labels) != len(preds):
        raise MXNetError("labels/preds length mismatch: %d vs %d"
                         % (len(labels), len(preds)))


class EvalMetric:
    """Base metric (ref: metric.py:EvalMetric)."""

    def __init__(self, name, output_names=None, label_names=None, **kwargs):
        self.name = str(name)
        self.output_names = output_names
        self.label_names = label_names
        self._kwargs = kwargs
        self.reset()

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0

    def update(self, labels, preds):  # pragma: no cover - abstract
        raise NotImplementedError

    def update_dict(self, label, pred):
        if self.output_names is not None:
            pred = [pred[name] for name in self.output_names]
        else:
            pred = list(pred.values())
        if self.label_names is not None:
            label = [label[name] for name in self.label_names]
        else:
            label = list(label.values())
        self.update(label, pred)

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, self.sum_metric / self.num_inst)

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))

    def __str__(self):
        return "EvalMetric: %s" % dict(self.get_name_value())


@register
class Accuracy(EvalMetric):
    def __init__(self, axis=1, name="accuracy", **kwargs):
        super().__init__(name, **kwargs)
        self.axis = axis

    def update(self, labels, preds):
        if isinstance(labels, (NDArray, _np.ndarray)):
            labels, preds = [labels], [preds]
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            p = _as_np(pred)
            l = _as_np(label).astype(_np.int64)
            if p.ndim > l.ndim:
                p = p.argmax(axis=self.axis)
            p = p.astype(_np.int64).reshape(-1)
            l = l.reshape(-1)
            self.sum_metric += (p == l).sum()
            self.num_inst += len(l)


@register
class TopKAccuracy(EvalMetric):
    def __init__(self, top_k=1, name="top_k_accuracy", **kwargs):
        super().__init__("%s_%d" % (name, top_k), **kwargs)
        self.top_k = top_k

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            p = _as_np(pred)
            l = _as_np(label).astype(_np.int64)
            order = _np.argsort(-p, axis=1)[:, :self.top_k]
            self.sum_metric += (order == l[:, None]).any(axis=1).sum()
            self.num_inst += len(l)


@register
class F1(EvalMetric):
    def __init__(self, name="f1", average="macro", **kwargs):
        super().__init__(name, **kwargs)
        self.average = average
        self.reset_stats()

    def reset_stats(self):
        self.tp = self.fp = self.fn = 0

    def reset(self):
        super().reset()
        if hasattr(self, "tp"):
            self.reset_stats()

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            p = _as_np(pred)
            l = _as_np(label).astype(_np.int64).reshape(-1)
            if p.ndim > 1:
                p = p.argmax(axis=1)
            p = p.astype(_np.int64).reshape(-1)
            self.tp += ((p == 1) & (l == 1)).sum()
            self.fp += ((p == 1) & (l == 0)).sum()
            self.fn += ((p == 0) & (l == 1)).sum()
            prec = self.tp / max(self.tp + self.fp, 1)
            rec = self.tp / max(self.tp + self.fn, 1)
            f1 = 2 * prec * rec / max(prec + rec, 1e-12)
            self.sum_metric = f1
            self.num_inst = 1


@register
class MCC(EvalMetric):
    """Matthews correlation (ref: metric.py:MCC)."""

    def __init__(self, name="mcc", **kwargs):
        super().__init__(name, **kwargs)
        self.tp = self.fp = self.tn = self.fn = 0

    def reset(self):
        super().reset()
        self.tp = self.fp = self.tn = self.fn = 0

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            p = _as_np(pred)
            l = _as_np(label).astype(_np.int64).reshape(-1)
            if p.ndim > 1:
                p = p.argmax(axis=1)
            p = p.astype(_np.int64).reshape(-1)
            self.tp += ((p == 1) & (l == 1)).sum()
            self.fp += ((p == 1) & (l == 0)).sum()
            self.tn += ((p == 0) & (l == 0)).sum()
            self.fn += ((p == 0) & (l == 1)).sum()
            denom = math.sqrt(max((self.tp + self.fp) * (self.tp + self.fn)
                                  * (self.tn + self.fp) * (self.tn + self.fn), 1))
            self.sum_metric = (self.tp * self.tn - self.fp * self.fn) / denom
            self.num_inst = 1


@register
class Perplexity(EvalMetric):
    def __init__(self, ignore_label=None, axis=-1, name="perplexity", **kwargs):
        super().__init__(name, **kwargs)
        self.ignore_label = ignore_label
        self.axis = axis

    def update(self, labels, preds):
        loss = 0.0
        num = 0
        for label, pred in zip(labels, preds):
            p = _as_np(pred)
            l = _as_np(label).astype(_np.int64).reshape(-1)
            p = p.reshape(-1, p.shape[-1])
            probs = p[_np.arange(len(l)), l]
            if self.ignore_label is not None:
                ignore = (l == self.ignore_label)
                probs = _np.where(ignore, 1.0, probs)
                num -= ignore.sum()
            loss -= _np.log(_np.maximum(probs, 1e-10)).sum()
            num += len(l)
        self.sum_metric += math.exp(loss / max(num, 1)) * num
        self.num_inst += num

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, self.sum_metric / self.num_inst)


@register
class MAE(EvalMetric):
    def __init__(self, name="mae", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            l, p = _as_np(label), _as_np(pred)
            if l.ndim == 1:
                l = l.reshape(-1, 1)
            self.sum_metric += _np.abs(l - p.reshape(l.shape)).mean()
            self.num_inst += 1


@register
class MSE(EvalMetric):
    def __init__(self, name="mse", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            l, p = _as_np(label), _as_np(pred)
            if l.ndim == 1:
                l = l.reshape(-1, 1)
            self.sum_metric += ((l - p.reshape(l.shape)) ** 2).mean()
            self.num_inst += 1


@register
class RMSE(MSE):
    def __init__(self, name="rmse", **kwargs):
        EvalMetric.__init__(self, name, **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            l, p = _as_np(label), _as_np(pred)
            if l.ndim == 1:
                l = l.reshape(-1, 1)
            self.sum_metric += math.sqrt(((l - p.reshape(l.shape)) ** 2).mean())
            self.num_inst += 1


@register
class CrossEntropy(EvalMetric):
    def __init__(self, eps=1e-12, name="cross-entropy", **kwargs):
        super().__init__(name, **kwargs)
        self.eps = eps

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            l = _as_np(label).astype(_np.int64).reshape(-1)
            p = _as_np(pred).reshape(len(l), -1)
            prob = p[_np.arange(len(l)), l]
            self.sum_metric += (-_np.log(prob + self.eps)).sum()
            self.num_inst += len(l)


@register
class NegativeLogLikelihood(CrossEntropy):
    def __init__(self, eps=1e-12, name="nll-loss", **kwargs):
        super().__init__(eps=eps, name=name, **kwargs)


@register
class PearsonCorrelation(EvalMetric):
    def __init__(self, name="pearsonr", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            l, p = _as_np(label).reshape(-1), _as_np(pred).reshape(-1)
            cc = _np.corrcoef(l, p)[0, 1]
            self.sum_metric += cc
            self.num_inst += 1


@register
class Loss(EvalMetric):
    """Mean of a loss output (ref: metric.py:Loss)."""

    def __init__(self, name="loss", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, _, preds):
        if isinstance(preds, (NDArray, _np.ndarray)):
            preds = [preds]
        for pred in preds:
            p = _as_np(pred)
            self.sum_metric += p.sum()
            self.num_inst += p.size


class CustomMetric(EvalMetric):
    def __init__(self, feval, name="custom", allow_extra_outputs=False, **kwargs):
        super().__init__("custom(%s)" % name, **kwargs)
        self._feval = feval
        self._allow_extra_outputs = allow_extra_outputs

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            reval = self._feval(_as_np(label), _as_np(pred))
            if isinstance(reval, tuple):
                m, n = reval
                self.sum_metric += m
                self.num_inst += n
            else:
                self.sum_metric += reval
                self.num_inst += 1


class CompositeEvalMetric(EvalMetric):
    def __init__(self, metrics=None, name="composite", **kwargs):
        super().__init__(name, **kwargs)
        self.metrics = metrics or []

    def add(self, metric):
        self.metrics.append(create(metric))

    def update(self, labels, preds):
        for m in self.metrics:
            m.update(labels, preds)

    def reset(self):
        for m in getattr(self, "metrics", []):
            m.reset()

    def get(self):
        names, vals = [], []
        for m in self.metrics:
            n, v = m.get()
            names.append(n)
            vals.append(v)
        return names, vals


def np_metric(numpy_feval, name=None, allow_extra_outputs=False):
    """Decorator creating a CustomMetric (ref: metric.py:np)."""
    def feval(label, pred):
        return numpy_feval(label, pred)
    feval.__name__ = name or numpy_feval.__name__
    return CustomMetric(feval, feval.__name__, allow_extra_outputs)


np = np_metric  # mx.metric.np parity (numpy is imported as _np to avoid clobbering)


def create(metric, *args, **kwargs):
    """Create a metric from name/callable/list (ref: metric.py:create)."""
    if callable(metric):
        return CustomMetric(metric, *args, **kwargs)
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, list):
        comp = CompositeEvalMetric()
        for m in metric:
            comp.add(create(m, *args, **kwargs))
        return comp
    if isinstance(metric, str):
        aliases = {"acc": "accuracy", "ce": "crossentropy", "nll_loss":
                   "negativeloglikelihood", "top_k_accuracy": "topkaccuracy",
                   "top_k_acc": "topkaccuracy"}
        key = aliases.get(metric.lower(), metric.lower()).replace("_", "").replace("-", "")
        lookup = {k.replace("_", ""): v for k, v in _REGISTRY.items()}
        if key not in lookup:
            raise MXNetError("Metric %s not registered" % metric)
        return lookup[key](*args, **kwargs)
    raise MXNetError("invalid metric spec %r" % (metric,))
