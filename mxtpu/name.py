"""Name scoping for symbol composition (ref: python/mxnet/name.py).

``NameManager`` auto-names anonymous symbols per op-type counter;
``Prefix`` prepends a fixed prefix — the mechanism behind
``with mx.name.Prefix("stage1_"): ...`` in reference model code. The
active manager is consulted by ``mx.sym`` op calls
(mxtpu/symbol/__init__.py _symbolic_call) when no ``name=`` is given.
"""
from __future__ import annotations

import threading

__all__ = ["NameManager", "Prefix", "current"]


class NameManager:
    """Thread-local stack of naming scopes (ref: name.py:NameManager)."""

    _state = threading.local()

    def __init__(self):
        self._counter = {}
        self._old = None

    def get(self, name, hint):
        """Return ``name`` if given, else generate ``<hint><n>``."""
        if name:
            return name
        c = self._counter.get(hint, 0)
        self._counter[hint] = c + 1
        return "%s%d" % (hint, c)

    def __enter__(self):
        stack = _stack()
        stack.append(self)
        return self

    def __exit__(self, *exc):
        _stack().pop()
        return False


class Prefix(NameManager):
    """Prepend a prefix to every auto-generated name (ref: name.py:Prefix)."""

    def __init__(self, prefix):
        super().__init__()
        self._prefix = prefix

    def get(self, name, hint):
        return self._prefix + super().get(name, hint)


def _stack():
    st = getattr(NameManager._state, "stack", None)
    if st is None:
        st = NameManager._state.stack = []
    return st


def current():
    """The innermost active NameManager, or None (module-global counters
    then name the symbol, preserving pre-scope behavior)."""
    st = _stack()
    return st[-1] if st else None
