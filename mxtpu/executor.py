"""``mx.executor`` namespace alias (ref: python/mxnet/executor.py — the
Executor class over MXExecutor* C calls). The TPU-native Executor lives
with the symbol layer (mxtpu/symbol/executor.py: jit-cached fwd/bwd over
the same tape); this module keeps ``mx.executor.Executor`` spelling and
isinstance checks working for code written against the reference.
"""
from .symbol.executor import Executor

__all__ = ["Executor"]
