"""Fleet observability plane (ISSUE 19).

Every observability surface built so far — the telemetry registry, the
causal-trace layer, the xprof ledger, ``perf.mfu``, Prometheus
``/metrics`` — is process-local; a 2-host fleet is two blind spots that
happen to share a checkpoint. This module federates them over the
ISSUE-18 fleet status board (``MXTPU_FLEET_DIR``), all host-side and
injected-clock testable, with zero device work:

* **Per-host publication** — :class:`HostObsPublisher` writes a compact,
  bounded snapshot blob ``obs_<rank>.json`` (atomic tmp+rename beside
  the heartbeat files): counters, gauges, histogram quantiles, the
  resolve-free xprof ledger digest, and the last-K trace-event tail.
  ``install()`` rides the telemetry flush hook so every sink flush —
  including the SIGTERM/atexit final flush — also refreshes the blob.
* **Coordinator merge** — :class:`FleetObservatory` folds all
  ``obs_*.json`` + heartbeat files into one fleet snapshot: per-host
  rows plus fleet aggregates (``fleet.mfu`` = ledger-FLOPs-weighted,
  ``fleet.step_s`` p50/p99 across hosts, per-host heartbeat age), a
  host-labeled Prometheus exposition (``host="<rank>"`` label family),
  and a ``refresh()`` that lands the aggregates in the local registry so
  the coordinator's existing ``/metrics`` serves the whole fleet.
* **Sentinels** — :class:`StragglerSentinel` keeps a rolling per-host
  baseline off the ``Fleet.step_barrier`` board payloads (stage
  breakdown + arrival timestamps): a rank persistently slower than
  ``MXTPU_STRAGGLER_X`` × the fleet median trips
  ``flight_record("straggler")`` naming the rank and its dominant
  stage; :class:`RegressionSentinel` watches one host's own rolling
  step time for slow drift (the gap the ISSUE-14 wedge watchdog's hard
  deadline can't see) and trips ``flight_record("step_regression")``.
  Either trip optionally arms ONE bounded ``jax.profiler`` capture
  window per trip reason (``MXTPU_PROFILE_ON_TRIP``), artifact beside
  the flight record.

The plane is opt-in (``MXTPU_FLEET_OBS_S``/``MXTPU_STRAGGLER_X`` both
default off) and purely additive: an observatory that dies degrades the
merged view to surviving hosts' blobs — training never depends on it.
"""

from __future__ import annotations

import glob as _glob
import json
import logging
import os
import statistics
import threading

from . import telemetry, xprof
from .fleet import _atomic_write

_log = logging.getLogger("mxtpu.fleet_obs")

__all__ = [
    "obs_interval_s", "straggler_x", "profile_on_trip",
    "host_snapshot", "publish_obs", "HostObsPublisher",
    "FleetObservatory", "StragglerSentinel", "RegressionSentinel",
    "step_traces",
]

# Bounds on the published blob: the board must stay cheap to write at
# flush cadence and cheap to re-read on every coordinator scrape.
TRACE_TAIL = 64
LEDGER_TOP = 16

# One bounded profiler window per trip reason per process; the window is
# a module constant (not an env lever) — trips are rare and the artifact
# only needs to straddle a few steps.
PROFILE_WINDOW_S = 1.0
_PROFILE_DONE = set()
_PROFILE_LOCK = threading.Lock()


# ------------------------------------------------------------- policies
def obs_interval_s():
    """Publication cadence for the per-host obs blob, seconds; 0 (the
    default) disables publication entirely."""
    try:
        return float(os.environ.get("MXTPU_FLEET_OBS_S", "0") or 0)  # graftlint: disable=policy-key-coverage
    except ValueError:
        return 0.0


def straggler_x():
    """Straggler threshold: a rank persistently slower than this factor
    × the fleet-median step time trips the sentinel; 0 (default) = off."""
    try:
        return float(os.environ.get("MXTPU_STRAGGLER_X", "0") or 0)  # graftlint: disable=policy-key-coverage
    except ValueError:
        return 0.0


def profile_on_trip():
    """When truthy, a sentinel trip arms one bounded ``jax.profiler``
    capture window per trip reason (artifact beside the flight record)."""
    return os.environ.get("MXTPU_PROFILE_ON_TRIP", "0") != "0"  # graftlint: disable=policy-key-coverage


# ------------------------------------------------- per-host publication
def _ledger_digest():
    """Resolve-free xprof view, bounded: the compile/HBM summary, the
    executed train-site FLOPs, and the top-N sites by executed FLOPs."""
    if not xprof.enabled():
        return None
    digest = {"summary": xprof.summary(),
              "executed_flops": xprof.executed_flops(xprof.TRAIN_SITES)}
    rows = []
    for e in xprof.ledger_snapshot():
        fl = e.get("flops") or 0
        rows.append({"site": e.get("site"), "calls": e.get("calls"),
                     "flops": fl,
                     "executed_flops": fl * (e.get("calls") or 0)})
    rows.sort(key=lambda r: -(r["executed_flops"] or 0))
    digest["sites"] = rows[:LEDGER_TOP]
    return digest


def host_snapshot(rank, step=None):
    """The bounded per-host blob :func:`publish_obs` writes: registry
    aggregates + ledger digest + trace-event tail. Pure host bookkeeping;
    never resolves an executable or touches a device."""
    snap = telemetry.snapshot()
    return {
        "rank": int(rank),
        "pid": os.getpid(),
        "step": None if step is None else int(step),
        "counters": snap["counters"],
        "gauges": snap["gauges"],
        "histograms": snap["histograms"],
        "retrace": snap["retrace"],
        "ledger": _ledger_digest(),
        "trace_tail": telemetry.trace_events()[-TRACE_TAIL:],
    }


def publish_obs(fleet_dir, rank, step=None, t=None):
    """Write this host's ``obs_<rank>.json`` into the fleet board
    (atomic tmp+rename, same discipline as the heartbeat files). Errors
    are counted, never raised — observability must not kill training."""
    path = os.path.join(fleet_dir, "obs_%d.json" % int(rank))
    try:
        blob = host_snapshot(rank, step=step)
        if t is not None:
            blob["t"] = t
        else:
            import time
            blob["t"] = time.time()
        _atomic_write(path, json.dumps(blob))
        telemetry.inc("fleet.obs.publishes")
        return path
    except Exception as e:  # pragma: no cover - defensive
        telemetry.inc("fleet.obs.errors")
        _log.warning("obs publish failed for rank %s: %s", rank, e)
        return None


class HostObsPublisher:
    """Cadenced writer of one host's obs blob. ``maybe_publish(step)``
    throttles to ``interval_s`` (default from ``MXTPU_FLEET_OBS_S``);
    ``install()`` additionally registers :meth:`publish` as a telemetry
    flush hook so the final SIGTERM/atexit flush also lands a blob —
    exactly the window a straggler/crash postmortem needs."""

    def __init__(self, fleet_dir, rank, interval_s=None, clock=None):
        import time
        self.fleet_dir = fleet_dir
        self.rank = int(rank)
        self.interval_s = (obs_interval_s() if interval_s is None
                           else float(interval_s))
        self._clock = clock or time.time
        self._last = None
        self._step = None
        self._installed = False

    @property
    def path(self):
        return os.path.join(self.fleet_dir, "obs_%d.json" % self.rank)

    def publish(self, step=None):
        if step is not None:
            self._step = int(step)
        out = publish_obs(self.fleet_dir, self.rank, step=self._step,
                          t=self._clock())
        self._last = self._clock()
        return out

    def maybe_publish(self, step=None):
        """Publish if the cadence window elapsed; returns the blob path
        or None. A non-positive interval disables the cadence path (the
        flush hook and explicit ``publish()`` still work)."""
        if step is not None:
            self._step = int(step)
        if self.interval_s <= 0:
            return None
        now = self._clock()
        if self._last is not None and now - self._last < self.interval_s:
            return None
        return self.publish()

    def install(self):
        """Ride every telemetry flush (periodic, explicit, and the
        atexit/SIGTERM final one)."""
        if not self._installed:
            telemetry.on_flush(self.publish)
            self._installed = True
        return self


# ------------------------------------------------------ coordinator side
def _median(vals):
    return statistics.median(vals) if vals else None


def _quantile(vals, q):
    if not vals:
        return None
    vals = sorted(vals)
    idx = min(int(q * len(vals)), len(vals) - 1)
    return vals[idx]


class FleetObservatory:
    """Coordinator-side merge of every host's obs blob + heartbeat into
    one fleet snapshot. Read-only over the board directory: a missing or
    torn blob degrades the view to surviving hosts, never raises."""

    def __init__(self, fleet_dir, num_hosts=None, clock=None):
        import time
        self.fleet_dir = fleet_dir
        self.num_hosts = num_hosts
        self._clock = clock or time.time

    def blobs(self):
        """``{rank: blob}`` for every readable ``obs_<rank>.json``."""
        out = {}
        for p in sorted(_glob.glob(
                os.path.join(self.fleet_dir, "obs_*.json"))):
            try:
                with open(p) as f:
                    blob = json.load(f)
                out[int(blob["rank"])] = blob
            except Exception:
                continue
        return out

    def heartbeats(self):
        """``{rank: heartbeat record}`` from the membership board."""
        out = {}
        for p in sorted(_glob.glob(
                os.path.join(self.fleet_dir, "host_*.json"))):
            try:
                with open(p) as f:
                    rec = json.load(f)
                out[int(rec["rank"])] = rec
            except Exception:
                continue
        return out

    def merged(self):
        """One fleet snapshot: per-host rows + fleet aggregates.

        ``fleet.mfu`` is the ledger-FLOPs-weighted mean of per-host
        ``perf.mfu`` (hosts execute different FLOPs under elastic
        membership — an unweighted mean would let an idle host drag the
        number); ``fleet.step_s`` p50/p99 are taken across the hosts'
        own ``trainer.step`` medians, so a single straggler shows up in
        the p99 without resolving anything."""
        now = self._clock()
        blobs = self.blobs()
        beats = self.heartbeats()
        hosts = {}
        for rank in sorted(set(blobs) | set(beats)):
            blob = blobs.get(rank) or {}
            beat = beats.get(rank) or {}
            gauges = blob.get("gauges") or {}
            hists = blob.get("histograms") or {}
            ledger = blob.get("ledger") or {}
            mfu = gauges.get("perf.mfu")
            if isinstance(mfu, dict):
                mfu = mfu.get("_untagged")
            step_h = hists.get("trainer.step") or {}
            hosts[rank] = {
                "rank": rank,
                "status": beat.get("status"),
                "step": blob.get("step", beat.get("step")),
                "pid": blob.get("pid", beat.get("pid")),
                "mfu": mfu,
                "executed_flops": ledger.get("executed_flops"),
                "step_s": {k: step_h.get(k)
                           for k in ("count", "p50", "p99", "max")},
                "heartbeat_age_s": (round(now - beat["t"], 3)
                                    if beat.get("t") is not None else None),
                "blob_age_s": (round(now - blob["t"], 3)
                               if blob.get("t") is not None else None),
            }
        fl_pairs = [(h["mfu"], h["executed_flops"])
                    for h in hosts.values() if h["mfu"] is not None]
        if fl_pairs:
            wsum = sum(fl or 0 for _, fl in fl_pairs)
            if wsum > 0:
                fleet_mfu = sum(m * (fl or 0) for m, fl in fl_pairs) / wsum
            else:
                fleet_mfu = sum(m for m, _ in fl_pairs) / len(fl_pairs)
        else:
            fleet_mfu = None
        p50s = [h["step_s"]["p50"] for h in hosts.values()
                if h["step_s"].get("p50") is not None]
        up = [r for r, h in hosts.items()
              if h["status"] not in (None, "left", "dead")]
        return {
            "t": now,
            "hosts": hosts,
            "fleet": {
                "mfu": fleet_mfu,
                "step_s": {"p50": _median(p50s),
                           "p99": _quantile(p50s, 0.99)},
                "hosts_up": len(up),
                "hosts_seen": len(hosts),
                "executed_flops": sum(h["executed_flops"] or 0
                                      for h in hosts.values()),
            },
        }

    def refresh(self):
        """Re-merge and land the fleet aggregates in the LOCAL registry
        (``fleet.mfu``, ``fleet.step_s{p50,p99}``, per-host heartbeat
        ages, ``fleet.hosts_up``) so the coordinator's existing
        ``/metrics`` and snapshot exports carry the whole fleet."""
        m = self.merged()
        fl = m["fleet"]
        if fl["mfu"] is not None:
            telemetry.gauge("fleet.mfu", fl["mfu"])
        for q in ("p50", "p99"):
            if fl["step_s"].get(q) is not None:
                telemetry.gauge("fleet.step_s", fl["step_s"][q], tag=q)
        telemetry.gauge("fleet.hosts_up", fl["hosts_up"])
        for rank, h in m["hosts"].items():
            if h["heartbeat_age_s"] is not None:
                telemetry.gauge("fleet.heartbeat_age_s",
                                h["heartbeat_age_s"],
                                tag="host%d" % rank)
        return m

    def prometheus(self):
        """Host-labeled exposition of every host's published counters,
        gauges, and histogram summaries: the registry's own family names
        with a ``host="<rank>"`` label (plus the usual ``tag`` label for
        tagged families). Registered via
        ``telemetry.register_prometheus_extra`` this makes one
        coordinator ``/metrics`` scrape cover the fleet."""
        self.refresh()
        pn, pl = telemetry._prom_name, telemetry._prom_label
        lines = []
        for rank, blob in sorted(self.blobs().items()):
            host = 'host="%s"' % pl(str(rank))
            for kind, typ in (("counters", "counter"), ("gauges", "gauge")):
                for name, val in sorted((blob.get(kind) or {}).items()):
                    base = pn(name)
                    lines.append("# TYPE %s %s" % (base, typ))
                    if isinstance(val, dict):
                        for tag, v in sorted(val.items()):
                            if tag == "_untagged":
                                lines.append("%s{%s} %s" % (base, host, v))
                            else:
                                lines.append('%s{%s,tag="%s"} %s'
                                             % (base, host, pl(tag), v))
                    else:
                        lines.append("%s{%s} %s" % (base, host, val))
            for name, h in sorted((blob.get("histograms") or {}).items()):
                base = pn(name)
                lines.append("# TYPE %s summary" % base)
                for q in ("p50", "p99"):
                    if h.get(q) is not None:
                        lines.append('%s{%s,quantile="%s"} %s'
                                     % (base, host, q[1:], h[q]))
                lines.append("%s_sum{%s} %s" % (base, host, h.get("sum", 0)))
                lines.append("%s_count{%s} %s"
                             % (base, host, h.get("count", 0)))
        return "\n".join(lines)

    def install(self):
        """Serve the fleet view from the coordinator's ``/metrics``."""
        telemetry.register_prometheus_extra(self.prometheus)
        return self


# ------------------------------------------------------------- sentinels
def _maybe_profile(reason):
    """Arm ONE bounded profiler capture window for this trip reason (a
    repeat trip is the same pathology; unbounded captures would be their
    own regression). Artifact lands beside the flight records; a stop
    timer bounds the window. No-op without ``MXTPU_PROFILE_ON_TRIP`` or
    a flight dir; never raises."""
    if not profile_on_trip():
        return None
    out_dir = telemetry.flight_dir()
    if out_dir is None:
        return None
    with _PROFILE_LOCK:
        if reason in _PROFILE_DONE:
            return None
        _PROFILE_DONE.add(reason)
    out = os.path.join(out_dir, "profile_%s_%d" % (reason, os.getpid()))
    try:
        import jax
        os.makedirs(out, exist_ok=True)
        jax.profiler.start_trace(out)

        def _stop():
            try:
                jax.profiler.stop_trace()
            except Exception:  # pragma: no cover - defensive
                pass

        timer = threading.Timer(PROFILE_WINDOW_S, _stop)
        timer.daemon = True
        timer.start()
        telemetry.inc("fleet.profile_captures", tag=str(reason))
        return out
    except Exception as e:  # pragma: no cover - defensive
        _log.warning("profile-on-trip (%s) failed: %s", reason, e)
        return None


def _stage_time(payload):
    """Total step seconds a board payload claims (dict payloads carry a
    ``stages`` breakdown; legacy list payloads carry none)."""
    if not isinstance(payload, dict):
        return None
    stages = payload.get("stages") or {}
    if not stages:
        return None
    return sum(v for v in stages.values() if v is not None)


class StragglerSentinel:
    """Names the slow rank. Feed it each step's ``Fleet.step_barrier``
    payload map; a rank above ``factor`` × the fleet-median step time for
    ``streak`` consecutive observed steps trips
    ``flight_record("straggler")`` with the laggard's stage breakdown,
    dominant stage, and ledger view, and bumps
    ``fleet.straggler_trips{host<r>}``. A recovered rank resets its
    streak and re-arms (the trip counter stays flat until it degrades
    again). Also gauges per-rank barrier-arrival skew."""

    def __init__(self, factor=None, streak=3):
        self.factor = straggler_x() if factor is None else float(factor)
        self.streak = max(int(streak), 1)
        self._streaks = {}
        self._tripped = set()
        self.trips = []

    def observe(self, step, payloads):
        """Returns the trip record if this observation tripped, else
        None. ``payloads`` is ``{rank: payload}`` as returned by
        ``Fleet.step_barrier`` — only dict payloads (obs-carrying) are
        considered."""
        if self.factor <= 0 or not payloads:
            return None
        arrivals = {r: p["t"] for r, p in payloads.items()
                    if isinstance(p, dict) and p.get("t") is not None}
        if arrivals:
            first = min(arrivals.values())
            for r, t in arrivals.items():
                telemetry.gauge("fleet.arrival_skew_s", round(t - first, 6),
                                tag="host%d" % r)
        times = {r: _stage_time(p) for r, p in payloads.items()}
        valid = [t for t in times.values() if t]
        if len(valid) < 2:
            return None
        med = _median(valid)
        trip = None
        for r, t in sorted(times.items()):
            if t is None:
                continue
            if med > 0 and t > self.factor * med:
                self._streaks[r] = self._streaks.get(r, 0) + 1
                if self._streaks[r] >= self.streak and r not in self._tripped:
                    trip = self._trip(step, r, t, med, payloads[r])
            else:
                self._streaks[r] = 0
                self._tripped.discard(r)
        return trip

    def _trip(self, step, rank, t, med, payload):
        self._tripped.add(rank)
        stages = payload.get("stages") or {}
        dominant = (max(stages.items(), key=lambda kv: kv[1] or 0)[0]
                    if stages else None)
        rec = {"rank": rank, "step": step, "step_s": t,
               "fleet_median_s": med,
               "ratio": round(t / med, 3) if med else None,
               "factor": self.factor, "stages": stages,
               "dominant_stage": dominant,
               "trace": payload.get("trace"),
               "ledger": _ledger_digest()}
        self.trips.append(rec)
        telemetry.inc("fleet.straggler_trips", tag="host%d" % rank)
        trace = payload.get("trace")
        telemetry.flight_record(
            "straggler", trace_ids=(trace,) if trace else (), extra=rec)
        _maybe_profile("straggler")
        return rec


class RegressionSentinel:
    """Same-host slow drift: the ISSUE-14 wedge watchdog fires on a hard
    deadline; this fires when the rolling RECENT step-time median climbs
    above ``factor`` × the rolling BASELINE median — a step that got 2×
    slower but still finishes never trips the watchdog, it trips here.
    Trips ``flight_record("step_regression")`` + ``fleet.step_regressions``
    once per excursion (re-arms when the recent window recovers)."""

    def __init__(self, factor=None, baseline_n=8, recent_n=4):
        self.factor = straggler_x() if factor is None else float(factor)
        self.baseline_n = max(int(baseline_n), 1)
        self.recent_n = max(int(recent_n), 1)
        self._hist = []
        self._tripped = False
        self.trips = []

    def observe(self, step, dur_s):
        """Feed one step's duration; returns the trip record or None."""
        if self.factor <= 0 or dur_s is None:
            return None
        self._hist.append(float(dur_s))
        bound = self.baseline_n + self.recent_n
        if len(self._hist) > bound:
            del self._hist[:-bound]
        if len(self._hist) < bound:
            return None
        baseline = _median(self._hist[:-self.recent_n])
        recent = _median(self._hist[-self.recent_n:])
        if baseline and recent > self.factor * baseline:
            if self._tripped:
                return None
            self._tripped = True
            rec = {"step": step, "baseline_s": baseline,
                   "recent_s": recent,
                   "ratio": round(recent / baseline, 3),
                   "factor": self.factor}
            self.trips.append(rec)
            telemetry.inc("fleet.step_regressions")
            telemetry.flight_record("step_regression", extra=rec)
            _maybe_profile("step_regression")
            return rec
        self._tripped = False
        return None


# ------------------------------------------------- cross-host stitching
def step_traces(fleet_dir, limit=None):
    """Per-step critical path off the ``barrier_step_*`` board dirs:
    for each step, which rank arrived last, by how much, and which stage
    of the laggard's breakdown dominated. Rows sorted by step; only
    dict (obs-carrying) payloads contribute."""
    rows = []
    for d in _glob.glob(os.path.join(fleet_dir, "barrier_step_*")):
        name = os.path.basename(d)
        try:
            step = int(name[len("barrier_step_"):])
        except ValueError:
            continue
        payloads = {}
        for p in _glob.glob(os.path.join(d, "host_*")):
            try:
                with open(p) as f:
                    rec = json.load(f)
                payloads[int(rec["rank"])] = rec.get("payload")
            except Exception:
                continue
        arrivals = {r: pl["t"] for r, pl in payloads.items()
                    if isinstance(pl, dict) and pl.get("t") is not None}
        times = {r: _stage_time(pl) for r, pl in payloads.items()}
        times = {r: t for r, t in times.items() if t is not None}
        if arrivals:
            last_rank = max(arrivals, key=arrivals.get)
            skew = arrivals[last_rank] - min(arrivals.values())
        elif times:
            last_rank = max(times, key=times.get)
            skew = None
        else:
            continue
        pl = payloads.get(last_rank) or {}
        stages = pl.get("stages") if isinstance(pl, dict) else None
        dominant = (max(stages.items(), key=lambda kv: kv[1] or 0)[0]
                    if stages else None)
        rows.append({
            "step": step, "ranks": len(payloads),
            "last_rank": last_rank,
            "skew_s": None if skew is None else round(skew, 6),
            "step_s": times.get(last_rank),
            "dominant_stage": dominant,
            "trace": pl.get("trace") if isinstance(pl, dict) else None,
            "stages": stages or {},
        })
    rows.sort(key=lambda r: r["step"])
    if limit is not None:
        rows = rows[-int(limit):]
    return rows
