"""Build/runtime feature introspection
(ref: python/mxnet/libinfo.py + MXGetVersion/runtime feature flags).

The reference reports compiled-in features (CUDA, CUDNN, MKLDNN, ...);
the TPU-native equivalents are runtime-discoverable facts about the jax
stack and the native library.
"""
from __future__ import annotations

__all__ = ["__version__", "features", "feature_list", "find_lib_path"]

__version__ = "0.3.0"  # round-numbered: bumped per build round


class Feature:
    __slots__ = ("name", "enabled")

    def __init__(self, name, enabled):
        self.name = name
        self.enabled = bool(enabled)

    def __repr__(self):
        return "[%s %s]" % ("+" if self.enabled else "-", self.name)


def features():
    """Dict of feature name -> enabled (ref: runtime feature flags)."""
    import jax

    out = {}
    try:
        platform = jax.devices()[0].platform
    except Exception:
        platform = "unknown"
    out["TPU"] = platform == "tpu"
    out["CPU_FALLBACK"] = platform == "cpu"
    try:
        from jax.experimental import pallas  # noqa: F401
        out["PALLAS"] = True
    except Exception:
        out["PALLAS"] = False
    # report from on-disk state — a diagnostics query must never trigger
    # the full native g++ build that get_lib() would kick off
    import ctypes
    import os as _os

    from ._native import _SO_PATH, build_error
    built = _os.path.exists(_SO_PATH)
    out["NATIVE_LIB"] = built
    has_c_api = has_recordio = False
    if built:
        try:
            _lib = ctypes.CDLL(_SO_PATH)
            has_c_api = hasattr(_lib, "MXTPUGetLastError")
            has_recordio = hasattr(_lib, "mxtpu_recordio_reader_create")
        except OSError:
            out["NATIVE_LIB"] = False
    out["C_API"] = has_c_api
    out["NATIVE_RECORDIO"] = has_recordio
    out["NATIVE_BUILD_ERROR"] = build_error() is not None
    try:
        import cv2  # noqa: F401
        out["OPENCV"] = True
    except Exception:
        out["OPENCV"] = False
    out["DISTRIBUTED"] = True  # jax.distributed is always importable
    return out


def feature_list():
    """List of Feature objects (ref: mx.runtime.feature_list)."""
    return [Feature(k, v) for k, v in sorted(features().items())]


def find_lib_path():
    """Path(s) to the native library (ref: libinfo.py:find_lib_path)."""
    import os

    from ._native import _SO_PATH
    return [_SO_PATH] if os.path.exists(_SO_PATH) else []
