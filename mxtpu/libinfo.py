"""Build/runtime feature introspection
(ref: python/mxnet/libinfo.py + MXGetVersion/runtime feature flags).

The reference reports compiled-in features (CUDA, CUDNN, MKLDNN, ...);
the TPU-native equivalents are runtime-discoverable facts about the jax
stack and the native library.
"""
from __future__ import annotations

__all__ = ["__version__", "features", "feature_list", "find_lib_path"]

__version__ = "0.3.0"  # round-numbered: bumped per build round


class Feature:
    __slots__ = ("name", "enabled")

    def __init__(self, name, enabled):
        self.name = name
        self.enabled = bool(enabled)

    def __repr__(self):
        return "[%s %s]" % ("+" if self.enabled else "-", self.name)


def features():
    """Dict of feature name -> enabled (ref: runtime feature flags)."""
    import jax

    out = {}
    try:
        platform = jax.devices()[0].platform
    except Exception:
        platform = "unknown"
    out["TPU"] = platform == "tpu"
    out["CPU_FALLBACK"] = platform == "cpu"
    try:
        from jax.experimental import pallas  # noqa: F401
        out["PALLAS"] = True
    except Exception:
        out["PALLAS"] = False
    from ._native import build_error, get_lib
    lib = get_lib()
    out["NATIVE_LIB"] = lib is not None
    out["C_API"] = lib is not None and hasattr(lib, "MXTPUGetLastError")
    out["NATIVE_RECORDIO"] = lib is not None and hasattr(
        lib, "mxtpu_recordio_reader_create")
    if lib is None and build_error() is not None:
        out["NATIVE_BUILD_ERROR"] = True
    try:
        import cv2  # noqa: F401
        out["OPENCV"] = True
    except Exception:
        out["OPENCV"] = False
    out["DISTRIBUTED"] = True  # jax.distributed is always importable
    return out


def feature_list():
    """List of Feature objects (ref: mx.runtime.feature_list)."""
    return [Feature(k, v) for k, v in sorted(features().items())]


def find_lib_path():
    """Path(s) to the native library (ref: libinfo.py:find_lib_path)."""
    import os

    from ._native import _SO_PATH
    return [_SO_PATH] if os.path.exists(_SO_PATH) else []
