"""2-bit gradient compression with error feedback
(ref: src/kvstore/gradient_compression.h:43-134, gradient_compression-inl.h).

Reference semantics, kept exactly: per element, the incoming gradient is
added to a persistent residual; elements whose residual crosses ±threshold
send ±threshold on the wire (2 bits each, 16 values per int32 in the
reference — here 4 per byte) and have the sent amount subtracted from the
residual, so quantization error feeds back into later pushes.

TPU-native placement: the reference compresses worker→server ps-lite
traffic. Here the data-plane gradient reduction inside jitted train steps
rides ICI, where compression is counterproductive — so compression applies
only to the KVStore dist_* control-plane path whose allreduce crosses DCN
(mxtpu/kvstore.py push), the exact link the reference built this for.
"""
from __future__ import annotations

import numpy as np

from .base import MXNetError

__all__ = ["GradientCompression"]


class GradientCompression:
    """Stateful quantizer: one residual buffer per key (per worker)."""

    def __init__(self, type="2bit", threshold=0.5, **_ignored):
        if type != "2bit":
            raise MXNetError("unsupported gradient compression type %r "
                             "(reference supports only 2bit too)" % type)
        self.threshold = float(threshold)
        if self.threshold <= 0:
            raise MXNetError("threshold must be positive")
        self._residuals = {}

    # wire codes: 0 -> 0, 1 -> +threshold, 2 -> -threshold (2 bits each)
    def quantize(self, key, grad):
        """Add grad to key's residual, emit packed 2-bit codes.

        Returns (packed_uint8, n_elements); updates the residual in place
        (gradient_compression-inl.h:67-77).
        """
        g = np.asarray(grad, np.float32).ravel()
        r = self._residuals.get(key)
        if r is None or r.shape != g.shape:
            r = np.zeros_like(g)
        r = r + g
        pos = r >= self.threshold
        neg = r <= -self.threshold
        codes = np.zeros(g.shape, np.uint8)
        codes[pos] = 1
        codes[neg] = 2
        r = r - pos * self.threshold + neg * self.threshold
        self._residuals[key] = r
        n = g.size
        pad = (-n) % 4
        codes = np.pad(codes, (0, pad))
        packed = (codes[0::4] | (codes[1::4] << 2) | (codes[2::4] << 4)
                  | (codes[3::4] << 6))
        return packed, n

    def dequantize(self, packed, n, shape=None):
        """Unpack 2-bit codes back to {-threshold, 0, +threshold} floats."""
        p = np.asarray(packed, np.uint8)
        codes = np.empty(p.size * 4, np.uint8)
        codes[0::4] = p & 3
        codes[1::4] = (p >> 2) & 3
        codes[2::4] = (p >> 4) & 3
        codes[3::4] = (p >> 6) & 3
        codes = codes[:n]
        out = np.zeros(n, np.float32)
        out[codes == 1] = self.threshold
        out[codes == 2] = -self.threshold
        return out.reshape(shape) if shape is not None else out

    def get_compression_factor(self):
        """Size reduction vs f32 (ref: GetCompressionFactor) — 16x."""
        return 16
