"""KVStore: parameter synchronization over XLA collectives.

Reference: ``include/mxnet/kvstore.h:59-411`` and ``src/kvstore/`` — `local`/`device`
reduce gradients across device copies (CommCPU/CommDevice/CommDeviceTree,
src/kvstore/comm.h, comm_tree.h), `nccl` uses ncclReduce/Bcast (kvstore_nccl.h), and
`dist_*` shards keys over ps-lite parameter servers (kvstore_dist.h).

TPU-native re-design (SURVEY §2.3 "→ TPU" and §5): there is ONE logical copy of each
parameter, laid out on the `jax.sharding.Mesh`. Cross-device reduction is an XLA
all-reduce riding ICI — the topology-aware tree building (gpu_topology.h's
Kernighan-Lin partitioning), P2P buffer heuristics, and NCCL integration are all
*subsumed* by the XLA collective layer, so this file replaces ~3k LoC of comm code
with sharding annotations. Multi-host (the reference's ps-lite path) is the same
collective spanning DCN via jax.distributed initialization — `dist_sync` and `nccl`
therefore share one implementation. `dist_async`'s parameter-server semantics have no
collective analog and raise (SURVEY §7 hard-part 5 scopes this to sync).

The data-plane reduction for the *fast path* happens inside jitted steps —
``mxtpu.parallel.ShardedTrainStep`` and the mesh-native ``gluon.Trainer``
(``Trainer(mesh=...)``), whose gradient reduction is GSPMD collectives
compiled into the donated fused update. With a mesh attached
(:meth:`KVStore.attach_mesh`, done by the Trainer at init) the device kind
is therefore a THIN CONTROL-PLANE VIEW over those same collectives: stored
values live as one logical replicated array on the mesh, so store-side
arithmetic (tree-sum merges, updater steps, row-sparse pulls) lowers to
the identical XLA collective layer, and the hot training loop never calls
push/pull at all — they remain the API for parameter init/broadcast,
occasional sync, and embedding pulls, exactly the reference's control
plane. This KVStore services the Trainer/Module API: Init/Push/Pull/
set_updater/rank/num_workers/Barrier, so frontend training loops run
unmodified.
"""
from __future__ import annotations

import pickle

import jax
import jax.numpy as jnp

from .base import MXNetError
from .ndarray import NDArray

__all__ = ["KVStore", "create"]


def _key_str(key):
    return str(key)


class KVStore:
    """Key-value store for parameter synchronization (ref: kvstore.h:59)."""

    def __init__(self, kind="local", mesh=None):
        self._kind = kind
        self._store = {}      # key -> NDArray (the merged/authoritative copy)
        self._updater = None
        self._optimizer = None
        self._compression = None
        self._mesh = mesh

    @property
    def type(self):
        return self._kind

    def attach_mesh(self, mesh):
        """Adopt a ``jax.sharding.Mesh``: subsequently-initialized keys are
        stored as ONE logical replicated array laid out on it, making this
        store a thin control-plane view over the mesh's collectives (module
        docstring). Called by ``gluon.Trainer(mesh=...)`` before init."""
        self._mesh = mesh

    # ------------------------------------------------------------------- init
    def init(self, key, value):
        """Initialize key(s) (ref: KVStore::Init; rank-0 broadcast semantics are
        trivial single-logical-copy here — on an attached mesh the stored
        copy is laid out replicated, the literal broadcast)."""
        keys, values = _normalize(key, value)
        for k, v in zip(keys, values):
            if k in self._store:
                continue
            # OWN copy, not an alias of the caller's buffer: the store-side
            # fused update (optimizer_fused.py) DONATES store weights to
            # XLA, which would delete a buffer the caller still reads
            if self._mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec
                d = jax.device_put(v._data,
                                   NamedSharding(self._mesh, PartitionSpec()))
                if d is v._data:  # already placed: device_put short-circuits
                    d = d.copy()
                self._store[k] = NDArray(d)
                continue
            self._store[k] = NDArray(jnp.asarray(v._data).copy())

    # -------------------------------------------------------------- push/pull
    def push(self, key, value, priority=0):
        """Aggregate value(s) into the store (ref: KVStoreLocal::PushImpl,
        src/kvstore/kvstore_local.h:184: comm_->Reduce then updater or merge).
        dist_sync additionally sums the merged value across every worker
        process — the reference's ps-lite server-side aggregation
        (kvstore_dist_server.h:155) becomes one DCN allreduce.

        .. note:: Keys pushed TOGETHER in one call fuse into ONE host-staged
           DCN allreduce per dtype (see :meth:`_dist_reduce`), so a grouped
           push — what Trainer does per step — costs O(1) network round
           trips, not O(keys). Still a CONTROL-PLANE path (parameter
           init/broadcast, occasional sync, embedding pulls): the training
           data plane is ``mxtpu.parallel.ShardedTrainStep``, whose gradient
           reduction is compiled into the step as XLA collectives and never
           touches the host.
        """
        keys, values = _normalize_grouped(key, value)
        merged_list = []
        for k, vs in zip(keys, values):
            if k not in self._store:
                raise MXNetError("key %s has not been initialized" % k)
            # reduce across "devices": with one logical copy this is a
            # tree-sum of the pushed list (ElementwiseSum,
            # src/ndarray/ndarray.cc:1280) — ONE fused stack-and-sum, not a
            # sequential a+b Python loop that would emit O(copies) adds
            if len(vs) == 1:
                merged = vs[0]._data
            else:
                merged = jnp.sum(jnp.stack([v._data for v in vs]), axis=0)
            merged_list.append(merged)
        if self._kind.startswith("dist"):
            merged_list = self._dist_reduce(keys, merged_list)
        if self._mesh is not None:
            # keep the store's invariant under pushes of un-placed values:
            # stored copies are ONE logical replicated array on the mesh
            from jax.sharding import NamedSharding, PartitionSpec
            repl = NamedSharding(self._mesh, PartitionSpec())
            merged_list = [jax.device_put(m, repl) for m in merged_list]
        if self._updater is None:
            for k, merged in zip(keys, merged_list):
                self._store[k]._set_data(merged)
            return
        if hasattr(self._updater, "update_batch"):
            # grouped push + store-side update: the whole key group updates
            # in ONE donated jit (FusedUpdater, mxtpu/optimizer_fused.py)
            self._updater.update_batch(
                [_int_key(k) for k in keys],
                [NDArray(m) for m in merged_list],
                [self._store[k] for k in keys])
        else:  # raw set_updater callables keep per-key semantics
            for k, merged in zip(keys, merged_list):
                self._updater(_int_key(k), NDArray(merged), self._store[k])

    def _dist_reduce(self, keys, merged_list):
        """Retrying wrapper over :meth:`_dist_reduce_once` for TRANSIENT
        failures (MXTPU_KV_RETRIES; the attempt is deterministic and
        side-effect-free locally, so re-running it is exact).

        Retry discipline: a retry is only collectively safe when EVERY
        participant observes the failure and retries in lockstep — a
        one-sided retry would pair one worker's fresh allgather with its
        peers' NEXT reduce and sum gradients across steps. Failures that
        reach python here before entering the collective (quantize/pack
        errors, injected faults, coordinator-reported aborts that raise on
        all workers) are that kind; a mid-collective partial failure is
        not. So multi-process worlds default to NO retries unless the
        operator opts in by setting MXTPU_KV_RETRIES explicitly, accepting
        that their failure mode raises everywhere (e.g. coordinator
        barrier errors). Single-process (and the CPU test tier) default to
        2. A persistent failure still raises — recovery is checkpoint +
        restart (see get_num_dead_node)."""
        import os as _os

        import jax as _jax

        from . import resilience
        if self._compression is not None:
            # NOT retry-safe: quantize folds the merged gradient into the
            # per-key error-feedback residual IN PLACE, so a second attempt
            # would double-count it — the compressed path fails fast
            retries = 0
        else:
            env = _os.environ.get("MXTPU_KV_RETRIES")
            if env is not None:
                retries = int(env)
            else:
                retries = 0 if _jax.process_count() > 1 else 2
        return resilience.with_retries(
            lambda: self._dist_reduce_once(keys, merged_list),
            what="kvstore dist gradient reduce",
            retries=retries, backoff=0.1, metric="retry.kvstore_reduce")

    def _dist_reduce_once(self, keys, merged_list):
        """Sum each local contribution across worker processes.

        Keys pushed TOGETHER in one call are FUSED into one flattened DCN
        round trip per dtype (inverse of the reference's big-array key
        sharding, src/kvstore/kvstore_dist.h:532: it splits one big array
        over servers; a collective wants many small arrays batched into
        one). A Trainer step that pushes its whole parameter list therefore
        costs O(1) allreduces, not O(keys) (VERDICT r4 item 8). With
        compression, the per-key 2-bit payloads concatenate into one
        allgather instead (ref: kvstore_dist.h PushCompressed semantics:
        only the packed wire format crosses the network; error feedback
        stays local)."""
        import numpy as np

        from . import distributed, resilience
        if resilience.inject("kv_fail"):
            raise MXNetError(
                "injected transient collective failure (MXTPU_FAULT_INJECT)")
        if self._compression is not None:
            out = []
            packed_all, meta = [], []
            for k, merged in zip(keys, merged_list):
                packed, n = self._compression.quantize(k, merged)
                packed = np.asarray(packed)
                meta.append((packed.shape[0], n, merged.shape, merged.dtype))
                packed_all.append(packed)
            wire = np.concatenate(packed_all) if packed_all else \
                np.zeros((0,), np.uint8)
            gathered = distributed.allgather_host(wire)  # ONE round trip
            for (plen, n, shape, dtype), off in zip(
                    meta, np.cumsum([0] + [m[0] for m in meta[:-1]])):
                summed = np.zeros(shape, np.float32)
                for row in gathered:
                    summed += self._compression.dequantize(
                        row[off:off + plen], n, shape)
                out.append(jnp.asarray(summed, dtype=dtype))
            return out
        # dense fuse: group same-dtype arrays into one flat vector
        by_dtype = {}
        for idx, merged in enumerate(merged_list):
            by_dtype.setdefault(np.dtype(merged.dtype), []).append(idx)
        out = list(merged_list)
        for dt, idxs in by_dtype.items():
            if len(idxs) == 1:
                i = idxs[0]
                out[i] = jnp.asarray(
                    distributed.allreduce_host(merged_list[i]))
                continue
            flats = [np.asarray(merged_list[i]).ravel() for i in idxs]
            sizes = [f.size for f in flats]
            reduced = distributed.allreduce_host(np.concatenate(flats))
            reduced = np.asarray(reduced)
            off = 0
            for i, sz in zip(idxs, sizes):
                out[i] = jnp.asarray(
                    reduced[off:off + sz].reshape(merged_list[i].shape),
                    dtype=dt)
                off += sz
        return out

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        """Copy current value into out (ref: KVStoreLocal::PullImpl)."""
        keys, outs = _normalize_grouped(key, out)
        donating = getattr(self._updater, "donates", False)
        for k, os_ in zip(keys, outs):
            if k not in self._store:
                raise MXNetError("key %s has not been initialized" % k)
            for o in os_:
                d = jnp.asarray(self._store[k]._data, dtype=o._data.dtype)
                if donating and d is self._store[k]._data:
                    # matching dtype aliases the store buffer zero-copy; the
                    # store-side fused update DONATES store buffers on the
                    # next push, which would delete the array handed out
                    # here — give the caller its own copy instead. With a
                    # non-donating updater (or none) keep the zero-copy
                    # alias on the Trainer gradient hot path.
                    d = d.copy()
                o._set_data(d)

    def pushpull(self, key, value, out=None, priority=0):
        self.push(key, value, priority)
        self.pull(key, out if out is not None else value, priority)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Pull only given rows (ref: KVStore::PullRowSparse, kvstore.h:235;
        dist row-sparse path kvstore_dist.h:448). TPU lowering: gather of the
        requested rows — across hosts this is an all-gather of ids + dynamic-slice."""
        if row_ids is None:
            raise MXNetError("row_sparse_pull requires row_ids")
        keys, outs = _normalize_grouped(key, out)
        rids = row_ids if isinstance(row_ids, (list, tuple)) else [row_ids]
        for k, os_ in zip(keys, outs):
            src = self._store[k]
            for o, rid in zip(os_, rids * len(os_)):
                rows = rid._data.astype(jnp.int32)
                from .ndarray.sparse import RowSparseNDArray
                vals = src._data[rows]
                if isinstance(o, RowSparseNDArray):
                    shape = o.shape
                    o._set_data(vals)
                    o._aux = {"indices": rows, "shape": tuple(shape)}
                else:
                    # dense out: scatter the pulled rows in place — the rest
                    # of the array is untouched (replacing the whole array
                    # with the gathered rows would destroy it)
                    o._set_data(o._data.at[rows].set(vals))

    # -------------------------------------------------------------- optimizer
    def set_updater(self, updater):
        """Run this updater on merged gradients (ref: KVStore::set_updater).

        Installing a batch updater re-owns every stored buffer (one-time
        copy): a prior no-updater push stores the caller's buffer as-is
        (cheap on the gradient hot path), and the fused update would
        otherwise DONATE — delete — an array the caller still holds."""
        if getattr(updater, "donates", False):
            for v in self._store.values():
                v._set_data(jnp.asarray(v._data).copy())
        self._updater = updater

    def set_optimizer(self, optimizer):
        from . import optimizer as opt_mod
        self._optimizer = optimizer
        self.set_updater(opt_mod.get_updater(optimizer))

    def set_gradient_compression(self, compression_params):
        """2-bit gradient compression with error feedback
        (ref: src/kvstore/gradient_compression.h). Active on the dist_*
        DCN allreduce path; the ICI data plane inside jitted steps stays
        uncompressed (bandwidth there makes compression counterproductive)."""
        from .gradient_compression import GradientCompression
        self._compression = GradientCompression(**dict(compression_params))

    # ------------------------------------------------------------ distributed
    @property
    def rank(self):
        return jax.process_index()

    @property
    def num_workers(self):
        return jax.process_count()

    def barrier(self):
        """Global barrier (ref: KVStore::Barrier → ps Postoffice barrier).
        Multi-process: a true cross-host rendezvous over DCN; single-process
        it is a no-op (nothing to wait for)."""
        from . import distributed
        distributed.barrier("mxtpu_kvstore_barrier")

    def _send_command_to_servers(self, head, body):
        """(ref: kvstore.py:616 → MXKVStoreSendCommmandToServers, used for
        server-side optimizer setup and kSetProfilerParams). This runtime
        has NO server processes by design (symmetric workers, README ADR):
        optimizer state lives in every worker (set_optimizer) and profiling
        is per-process (mx.profiler / MXTPU_PROFILER_AUTOSTART), so there
        is nowhere to send a command. Raises with that guidance instead of
        silently dropping the command."""
        raise MXNetError(
            "no parameter-server processes exist in this runtime "
            "(symmetric workers — README ADR). Server-side optimizer setup "
            "is set_optimizer() on each worker; server profiling is "
            "per-process mx.profiler (MXTPU_PROFILER_AUTOSTART=1 for "
            "whole-program capture).")

    def get_num_dead_node(self, node_id=0, timeout=60):
        """Failure-detection parity (ref: kvstore.h:353 — ps-lite heartbeat
        dead-node counts). The TPU runtime has no heartbeat-and-continue
        mode: XLA collectives FAIL FAST when a participant disappears (the
        surviving processes get a hard error at the next collective, not a
        degraded world), so while this process is alive the observable dead
        count is 0 — recovery is checkpoint + restart, the same story as
        the reference's distributed docs (SURVEY §5). Kept so monitoring
        loops written against the reference run unmodified."""
        return 0

    def save_optimizer_states(self, fname, dump_optimizer=False):
        if self._updater is None:
            raise MXNetError("there is no optimizer set, cannot save states")
        with open(fname, "wb") as f:
            f.write(self._updater.get_states(dump_optimizer))

    def load_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("there is no optimizer set, cannot load states")
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())


def _int_key(k):
    try:
        return int(k)
    except ValueError:
        return k


def _normalize(key, value):
    if isinstance(key, (list, tuple)):
        out_v = []
        for v in value:
            out_v.append(v)
        return [_key_str(k) for k in key], out_v
    return [_key_str(key)], [value]


def _normalize_grouped(key, value):
    """Group values per key (a key may receive a list of per-device values)."""
    if isinstance(key, (list, tuple)):
        keys = [_key_str(k) for k in key]
        if len(value) == len(keys) and all(
                isinstance(v, (list, tuple)) for v in value):
            return keys, [list(v) for v in value]
        if len(value) == len(keys):
            return keys, [[v] for v in value]
        per = len(value) // len(keys)
        return keys, [list(value[i * per:(i + 1) * per]) for i in range(len(keys))]
    vs = value if isinstance(value, (list, tuple)) else [value]
    return [_key_str(key)], [list(vs)]


def create(name="local", mesh=None):
    """Factory (ref: src/kvstore/kvstore.cc:40-72). `local`, `device`, and `nccl`
    collapse to the same XLA-collective store; `dist_sync*` requires
    jax.distributed multi-process initialization. ``mesh`` pre-attaches a
    ``jax.sharding.Mesh`` (see :meth:`KVStore.attach_mesh`)."""
    if not isinstance(name, str):
        raise TypeError("name must be a string")
    if name in ("local", "local_update_cpu", "local_allreduce_cpu",
                "local_allreduce_device", "device", "nccl"):
        return KVStore(name, mesh=mesh)
    if name in ("dist_sync", "dist_sync_device"):
        if mesh is not None:
            raise MXNetError(
                "kvstore %r cannot pre-attach a mesh: a multi-host mesh "
                "IS the distributed path (one mesh over jax.distributed "
                "processes, collectives over DCN) — use a device kind "
                "with the mesh instead" % name)
        from . import distributed
        if not distributed.is_initialized():
            raise MXNetError(
                "kvstore %r needs the multi-process runtime: call "
                "mxtpu.fleet.init() (coordinated bring-up: bounded-retry "
                "join + deadline barrier + heartbeat membership — "
                "docs/parallelism.md) or the bare mxtpu.distributed.init() "
                "first (env bootstrap: MXTPU_COORDINATOR/"
                "MXTPU_NUM_PROCESSES/MXTPU_PROCESS_ID or the reference's "
                "DMLC_* names; see tools/launch.py). The fleet path is the "
                "parity story for the reference's dist kvstore: the ps-lite "
                "scheduler/worker rendezvous becomes one symmetric join, "
                "and push/pull becomes XLA collectives on the global mesh. "
                "Refusing to silently fall back to the single-process store."
                % name)
        return KVStore(name)
    if name in ("dist_async", "dist"):
        # ADR (deliberate scope decision, VERDICT r2 item 8): dist_async is
        # NOT implemented, by design. The reference's async parameter server
        # (kvstore_dist_server.h:46 kSyncMode off) exists to hide stragglers
        # on heterogeneous GPU clusters by applying updates the moment any
        # worker pushes. A TPU pod is a synchronous machine: every chip runs
        # the same XLA program in lockstep and the gradient reduction IS part
        # of the compiled step over ICI, so there are no stragglers for
        # asynchrony to hide — async would only reintroduce stale-gradient
        # convergence risk for zero latency win. A host-side async parameter
        # service (SURVEY §7 hard-part 5) earns its complexity only for
        # DCN-sharded giant embeddings, which this framework serves instead
        # via row_sparse pull on the sync path. See README "dist_async".
        raise MXNetError(
            "dist_async is deliberately unsupported on TPU (synchronous "
            "lockstep machine; no stragglers to hide — see README). "
            "Use dist_sync after mxtpu.fleet.init() — the elastic "
            "multi-host bring-up (docs/parallelism.md) — or pass a "
            "multi-host mesh straight to gluon.Trainer(mesh=...).")
    raise MXNetError("unknown KVStore type %s" % name)
