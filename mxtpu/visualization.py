"""Network visualization (ref: python/mxnet/visualization.py).

``print_summary`` renders the layer-by-layer table (output shapes +
parameter counts) to stdout; ``plot_network`` builds a graphviz Digraph
when the ``graphviz`` package is importable (not bundled in this image —
the function raises a clear ImportError otherwise, like the reference).
"""
from __future__ import annotations

__all__ = ["print_summary", "plot_network"]


_PARAM_SUFFIXES = ("_weight", "_bias", "_gamma", "_beta", "_moving_mean",
                   "_moving_var", "_running_mean", "_running_var")


def _param_vars_of(node, shape_map):
    """(name, size) of this node's parameter inputs — identified by the
    parameter-name suffixes like the reference (visualization.py counts
    weight/bias/gamma/beta), never by excluding data-ish names."""
    import numpy as _np
    out = []
    for inp, _idx in node.inputs:
        if inp.is_var() and inp.name in shape_map and \
                inp.name.endswith(_PARAM_SUFFIXES):
            out.append((inp.name, int(_np.prod(shape_map[inp.name]))))
    return out


def print_summary(symbol, shape=None, line_length=120, positions=None):
    """Layer table like the reference's print_summary (visualization.py:38).

    ``shape``: dict of input name -> shape used to run shape inference so
    output shapes and parameter counts are concrete.
    """
    from .symbol.symbol import _topo

    shape_map = {}
    out_shapes = {}
    if shape:
        arg_shapes, _outs, aux_shapes = symbol.infer_shape(**shape)
        args = symbol.list_arguments()
        auxs = symbol.list_auxiliary_states()
        shape_map = dict(zip(args, arg_shapes))
        shape_map.update(dict(zip(auxs, aux_shapes)))
        internals = symbol.get_internals()
        try:
            _a, int_outs, _x = internals.infer_shape(**shape)
            for name, s in zip(
                    [n.name for n in _topo(internals._heads)], int_outs):
                out_shapes[name] = s
        except Exception:  # noqa: BLE001 - summary stays best-effort
            pass

    positions = positions or [0.44, 0.64, 0.74, 1.0]
    cols = [int(line_length * p) for p in positions]
    header = ["Layer (type)", "Output Shape", "Param #", "Previous Layer"]

    def row(fields):
        line = ""
        for text, stop in zip(fields, cols):
            line = (line + str(text))[:stop].ljust(stop)
        print(line)

    print("_" * line_length)
    row(header)
    print("=" * line_length)
    total = 0
    counted = set()  # a weight shared by two layers counts once in total
    nodes = [n for n in _topo(symbol._heads) if not n.is_var()]
    for node in nodes:
        pvars = _param_vars_of(node, shape_map)
        nparam = sum(sz for _n, sz in pvars)
        total += sum(sz for n_, sz in pvars if n_ not in counted)
        counted.update(n_ for n_, _sz in pvars)
        prev = ",".join(i.name for i, _ in node.inputs if not i.is_var())
        row(["%s (%s)" % (node.name, node.op),
             out_shapes.get(node.name, ""), nparam, prev])
    print("=" * line_length)
    print("Total params: {:,}".format(total))
    print("_" * line_length)
    return total


def plot_network(symbol, title="plot", save_format="pdf", shape=None,
                 node_attrs=None, hide_weights=True):
    """graphviz Digraph of the symbol (ref: visualization.py:plot_network).
    Requires the optional ``graphviz`` package."""
    try:
        from graphviz import Digraph
    except ImportError as e:  # pragma: no cover - graphviz not in image
        raise ImportError(
            "plot_network requires the python graphviz package") from e
    from .symbol.symbol import _topo

    attrs = {"shape": "box", "fixedsize": "false"}
    attrs.update(node_attrs or {})  # caller customization wins
    node_attrs = attrs
    dot = Digraph(name=title, format=save_format)
    for node in _topo(symbol._heads):
        if node.is_var():
            if hide_weights and node.name.endswith(
                    ("_weight", "_bias", "_gamma", "_beta", "_moving_mean",
                     "_moving_var")):
                continue
            dot.node(node.name, label=node.name,
                     **{**node_attrs, "shape": "oval"})
        else:
            dot.node(node.name, label="%s\n%s" % (node.name, node.op),
                     **node_attrs)
        for inp, _i in node.inputs:
            if inp.is_var() and hide_weights and inp.name.endswith(
                    ("_weight", "_bias", "_gamma", "_beta", "_moving_mean",
                     "_moving_var")):
                continue
            dot.edge(inp.name, node.name)
    return dot
