"""Sampling ops (ref: src/operator/random/sample_op.cc, multisample_op.cc,
sample_multinomial_op.cc, shuffle_op.cc — backed there by per-ctx PRNG resources,
here by JAX functional PRNG keys drawn at call time from mxtpu.random).

None of these are registered with wrap=True: the key must be fixed *before* taping
(see statefulness note in ops/nn.py), and sampling ops are non-differentiable leaves
anyway, so they return fresh untaped NDArrays.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..ndarray.ndarray import NDArray, _as_jax_dtype
from ..random import next_key
from .registry import register


def _shape(shape):
    if shape is None:
        return ()
    if isinstance(shape, int):
        return (shape,)
    return tuple(shape)


def _dt(dtype):
    if dtype in (None, "None"):
        return jnp.float32
    return _as_jax_dtype(dtype)


@register("uniform", aliases=("_random_uniform", "random_uniform"), wrap=False)
def uniform(low=0.0, high=1.0, shape=None, dtype=None, ctx=None, out=None, **_ig):
    if isinstance(low, NDArray):  # broadcastable param form (multisample)
        shape = jnp.broadcast_shapes(low.shape, high.shape if isinstance(high, NDArray) else ()) \
            + _shape(shape)
        lo = low._data if isinstance(low, NDArray) else low
        hi = high._data if isinstance(high, NDArray) else high
        d = jax.random.uniform(next_key(), shape, _dt(dtype)) * (hi - lo) + lo
    else:
        d = jax.random.uniform(next_key(), _shape(shape), _dt(dtype), low, high)
    r = NDArray(d)
    if out is not None:
        out._set_data(r._data)
        return out
    return r


@register("normal", aliases=("_random_normal", "random_normal", "randn"), wrap=False)
def normal(loc=0.0, scale=1.0, shape=None, dtype=None, ctx=None, out=None, **_ig):
    if isinstance(loc, NDArray) or isinstance(scale, NDArray):
        lo = loc._data if isinstance(loc, NDArray) else loc
        sc = scale._data if isinstance(scale, NDArray) else scale
        base = jnp.broadcast_shapes(jnp.shape(lo), jnp.shape(sc)) + _shape(shape)
        d = jax.random.normal(next_key(), base, _dt(dtype)) * sc + lo
    else:
        d = jax.random.normal(next_key(), _shape(shape), _dt(dtype)) * scale + loc
    r = NDArray(d)
    if out is not None:
        out._set_data(r._data)
        return out
    return r


@register("_random_gamma",
          aliases=("random_gamma", "sample_gamma", "_sample_gamma"),
          wrap=False)
def gamma_sample(alpha=1.0, beta=1.0, shape=None, dtype=None, ctx=None, out=None, **_ig):
    """Gamma sampler (ref: _random_gamma / _sample_gamma). Registered under
    the _random_ name only: the PRIMARY name ``gamma`` belongs to the
    elementwise tgamma (elemwise.py), exactly as in the reference where
    mx.nd.gamma is the gamma *function* — registering the sampler over it
    shadowed the math op through round 3."""
    a = alpha._data if isinstance(alpha, NDArray) else alpha
    b = beta._data if isinstance(beta, NDArray) else beta
    base = jnp.broadcast_shapes(jnp.shape(a), jnp.shape(b)) + _shape(shape)
    d = jax.random.gamma(next_key(), a, base, _dt(dtype)) * b
    r = NDArray(d)
    if out is not None:
        out._set_data(r._data)
        return out
    return r


@register("exponential", aliases=("_random_exponential", "random_exponential"), wrap=False)
def exponential(lam=1.0, shape=None, dtype=None, ctx=None, out=None, **_ig):
    lm = lam._data if isinstance(lam, NDArray) else lam
    base = jnp.broadcast_shapes(jnp.shape(lm)) + _shape(shape)
    d = jax.random.exponential(next_key(), base, _dt(dtype)) / lm
    return NDArray(d)


@register("poisson", aliases=("_random_poisson", "random_poisson"), wrap=False)
def poisson(lam=1.0, shape=None, dtype=None, ctx=None, out=None, **_ig):
    lm = lam._data if isinstance(lam, NDArray) else lam
    base = jnp.broadcast_shapes(jnp.shape(lm)) + _shape(shape)
    d = jax.random.poisson(next_key(), lm, base).astype(_dt(dtype))
    return NDArray(d)


@register("negative_binomial", aliases=("_random_negative_binomial",), wrap=False)
def negative_binomial(k=1, p=1.0, shape=None, dtype=None, ctx=None, **_ig):
    # NB(k,p) = Poisson(Gamma(k, (1-p)/p))
    kk = k._data if isinstance(k, NDArray) else k
    pp = p._data if isinstance(p, NDArray) else p
    base = jnp.broadcast_shapes(jnp.shape(kk), jnp.shape(pp)) + _shape(shape)
    lam = jax.random.gamma(next_key(), kk, base) * (1.0 - pp) / pp
    return NDArray(jax.random.poisson(next_key(), lam, base).astype(_dt(dtype)))


@register("generalized_negative_binomial",
          aliases=("_random_generalized_negative_binomial",), wrap=False)
def generalized_negative_binomial(mu=1.0, alpha=1.0, shape=None, dtype=None, ctx=None, **_ig):
    m = mu._data if isinstance(mu, NDArray) else mu
    a = alpha._data if isinstance(alpha, NDArray) else alpha
    base = jnp.broadcast_shapes(jnp.shape(m), jnp.shape(a)) + _shape(shape)
    # GNB: Poisson with Gamma(1/alpha, alpha*mu) mixture
    lam = jax.random.gamma(next_key(), 1.0 / a, base) * a * m
    return NDArray(jax.random.poisson(next_key(), lam, base).astype(_dt(dtype)))


@register("randint", aliases=("_random_randint", "random_randint"), wrap=False)
def randint(low=0, high=None, shape=None, dtype="int32", ctx=None, **_ig):
    d = jax.random.randint(next_key(), _shape(shape), low, high,
                           _as_jax_dtype(dtype if dtype != "None" else "int32"))
    return NDArray(d)


@register("multinomial", aliases=("_sample_multinomial", "sample_multinomial"), wrap=False)
def multinomial(data, shape=None, get_prob=False, dtype="int32", **_ig):
    """Sample category ids from (batched) distributions
    (ref: src/operator/random/sample_multinomial_op.cc)."""
    p = data._data
    n = 1 if shape in (None, ()) else (shape if isinstance(shape, int) else int(jnp.prod(jnp.asarray(shape))))
    logits = jnp.log(jnp.maximum(p, 1e-30))
    if p.ndim == 1:
        out = jax.random.categorical(next_key(), logits, shape=(n,))
        out = out[0] if shape in (None, ()) else out
    else:
        out = jax.random.categorical(next_key(), logits[:, None, :].repeat(n, 1), axis=-1)
        out = out[:, 0] if shape in (None, ()) else out
    res = NDArray(out.astype(_as_jax_dtype(dtype)))
    if get_prob:
        lp = jnp.take_along_axis(jax.nn.log_softmax(logits, -1),
                                 jnp.atleast_1d(out)[..., None].astype(jnp.int32), -1)[..., 0]
        return [res, NDArray(lp)]
    return res


@register("_sample_unique_zipfian", wrap=False, num_outputs=2)
def _sample_unique_zipfian(range_max, shape=None, **_ig):
    """Per-row unique samples from the approx-Zipfian (log-uniform)
    distribution over [0, range_max): value = round(exp(u * ln(range_max)))-1
    rejection-sampled without replacement, plus the per-row try counts used
    to derive expected counts in candidate sampling / NCE (ref:
    src/operator/random/unique_sample_op.{h,cc} UniqueSampleUniformKernel —
    a CPU-only kernel there too; the data-dependent rejection loop is host
    work by design, feeding device-side NCE training)."""
    import numpy as _onp
    shp = _shape(shape)
    if len(shp) != 2:
        raise ValueError("_sample_unique_zipfian needs a 2-D shape, got %r"
                         % (shape,))
    batch, num_sampled = shp
    if num_sampled > range_max:
        raise ValueError("cannot draw %d unique samples from range_max=%d"
                         % (num_sampled, range_max))
    if range_max >= 2**31:
        # the reference emits int64; device arrays here are int32 under
        # jax's default x64-off config, so huge id spaces would wrap
        raise ValueError("range_max %d exceeds int32 id space" % range_max)
    # derive a host RNG stream from the framework's functional key so runs
    # seeded via mxtpu.random.seed reproduce
    seed = int(jax.random.randint(next_key(), (), 0, 2**31 - 1))
    rng = _onp.random.default_rng(seed)
    log_range = _onp.log(range_max)
    samples = _onp.empty((batch, num_sampled), dtype=_onp.int32)
    tries = _onp.empty((batch,), dtype=_onp.int32)
    for i in range(batch):
        seen = set()
        t = 0
        while len(seen) < num_sampled:
            # draw a chunk; rejection keeps only first-seen values
            draw = _onp.floor(
                _onp.exp(rng.random(max(num_sampled, 16)) * log_range) + 0.5
            ).astype(_onp.int32) - 1
            for v in draw:
                t += 1
                if v not in seen:
                    samples[i, len(seen)] = v
                    seen.add(int(v))
                    if len(seen) == num_sampled:
                        break
        tries[i] = t
    return [NDArray(jnp.asarray(samples)), NDArray(jnp.asarray(tries))]


@register("shuffle", aliases=("_shuffle",), wrap=False)
def shuffle(data, **_ig):
    """Shuffle along axis 0 (ref: src/operator/random/shuffle_op.cc)."""
    return NDArray(jax.random.permutation(next_key(), data._data, axis=0))


# *_like variants (ref: sample_op.cc *_like registrations)
@register("uniform_like", wrap=False)
def uniform_like(data, low=0.0, high=1.0, **_ig):
    return NDArray(jax.random.uniform(next_key(), data.shape, jnp.float32, low, high)
                   .astype(data._data.dtype))


@register("normal_like", wrap=False)
def normal_like(data, loc=0.0, scale=1.0, **_ig):
    return NDArray((jax.random.normal(next_key(), data.shape) * scale + loc)
                   .astype(data._data.dtype))
