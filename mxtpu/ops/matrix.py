"""Shape-manipulation, indexing, ordering and dot ops.

Reference: src/operator/tensor/matrix_op.cc (reshape/transpose/slice/clip/repeat/tile/
flip/depth-space), indexing_op.cc (take/Embedding/gather_nd/scatter_nd/one_hot),
ordering_op.cc (sort/argsort/topk), dot-inl.h (dot/batch_dot), init_op.cc.
All become single XLA HLOs; the reference's hand-written CUDA gather/scatter/sort
kernels are subsumed by XLA's lowering (sort → variadic HLO Sort, take → Gather).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .precision_util import contract_acc, mxu_precision
from .registry import register, register_param_shapes


# ------------------------------------------------------------------ shape
@register("Reshape", aliases=("reshape",), as_method=False)
def Reshape(x, shape=None, reverse=False, **_ig):
    """MXNet reshape with special codes 0 (copy dim) and -1 (infer); -2/-3/-4 codes
    (ref matrix_op.cc ReshapeParam) supported for the common cases."""
    src = list(x.shape)
    if shape is None:
        raise ValueError("reshape requires target shape")
    tgt = []
    src_i = 0
    shape = list(shape)
    i = 0
    while i < len(shape):
        s = shape[i]
        if s == 0:
            tgt.append(src[src_i]); src_i += 1
        elif s == -1:
            tgt.append(-1); src_i += 1
        elif s == -2:  # copy all remaining dims
            tgt.extend(src[src_i:]); src_i = len(src)
        elif s == -3:  # merge two dims
            tgt.append(src[src_i] * src[src_i + 1]); src_i += 2
        elif s == -4:  # split dim into next two values
            a, b = shape[i + 1], shape[i + 2]
            dim = src[src_i]
            if a == -1:
                a = dim // b
            if b == -1:
                b = dim // a
            tgt.extend([a, b]); src_i += 1; i += 2
        else:
            tgt.append(s); src_i += 1
        i += 1
    return jnp.reshape(x, tuple(tgt))


@register("Flatten", aliases=("flatten",), as_method=False)
def Flatten(x):
    return jnp.reshape(x, (x.shape[0], -1))


@register("transpose", as_method=False)
def transpose(x, axes=None):
    axes = tuple(axes) if axes else None
    return jnp.transpose(x, axes)


@register("expand_dims", as_method=False)
def expand_dims(x, axis):
    return jnp.expand_dims(x, axis)


@register("squeeze", as_method=False)
def squeeze(x, axis=None):
    return jnp.squeeze(x, axis)


@register("Concat", aliases=("concat", "concatenate"), as_method=False)
def Concat(*args, dim=1, axis=None, num_args=None):
    ax = axis if axis is not None else dim
    return jnp.concatenate(args, axis=ax)


@register("stack", as_method=False)
def stack(*args, axis=0, num_args=None):
    return jnp.stack(args, axis=axis)


@register("SliceChannel", aliases=("split",), as_method=False)
def SliceChannel(x, num_outputs=1, axis=1, squeeze_axis=False):
    outs = jnp.split(x, num_outputs, axis=axis)
    if squeeze_axis:
        outs = [jnp.squeeze(o, axis=axis) for o in outs]
    return outs if num_outputs > 1 else outs[0]


@register("slice", aliases=("crop",), as_method=False)
def slice_(x, begin=(), end=(), step=()):
    idx = []
    step = step or [None] * len(begin)
    for b, e, s in zip(begin, end, step):
        idx.append(slice(b, e, s))
    return x[tuple(idx)]


@register("slice_axis", as_method=True)
def slice_axis(x, axis=0, begin=0, end=None):
    idx = [slice(None)] * x.ndim
    idx[axis] = slice(begin, end)
    return x[tuple(idx)]


@register("slice_like", as_method=True)
def slice_like(x, shape_like, axes=()):
    axes = axes or range(min(x.ndim, shape_like.ndim))
    idx = [slice(None)] * x.ndim
    for a in axes:
        idx[a] = slice(0, shape_like.shape[a])
    return x[tuple(idx)]


@register("tile", as_method=True)
def tile(x, reps=()):
    return jnp.tile(x, tuple(reps))


@register("repeat", as_method=True)
def repeat(x, repeats=1, axis=None):
    return jnp.repeat(x, repeats, axis=axis)


@register("pad", aliases=("Pad",), as_method=True)
def pad(x, mode="constant", pad_width=(), constant_value=0.0):
    pw = [(pad_width[2 * i], pad_width[2 * i + 1]) for i in range(len(pad_width) // 2)]
    jmode = {"constant": "constant", "edge": "edge", "reflect": "reflect"}[mode]
    if jmode == "constant":
        return jnp.pad(x, pw, mode=jmode, constant_values=constant_value)
    return jnp.pad(x, pw, mode=jmode)


@register("reverse", aliases=("flip",), as_method=True)
def reverse(x, axis=()):
    if isinstance(axis, int):
        axis = (axis,)
    return jnp.flip(x, axis=tuple(axis))


@register("depth_to_space")
def depth_to_space(x, block_size):
    n, c, h, w = x.shape
    b = block_size
    y = jnp.reshape(x, (n, b, b, c // (b * b), h, w))
    y = jnp.transpose(y, (0, 3, 4, 1, 5, 2))
    return jnp.reshape(y, (n, c // (b * b), h * b, w * b))


@register("space_to_depth")
def space_to_depth(x, block_size):
    n, c, h, w = x.shape
    b = block_size
    y = jnp.reshape(x, (n, c, h // b, b, w // b, b))
    y = jnp.transpose(y, (0, 3, 5, 1, 2, 4))
    return jnp.reshape(y, (n, c * b * b, h // b, w // b))


@register("diag", as_method=True)
def diag(x, k=0, **_ig):
    if x.ndim == 1:
        return jnp.diag(x, k=k)
    return jnp.diagonal(x, offset=k, axis1=-2, axis2=-1)


@register("swapaxes", aliases=("SwapAxis",), as_method=False)
def swapaxes(x, dim1=0, dim2=0):
    return jnp.swapaxes(x, dim1, dim2)


@register("shape_array")
def shape_array(x):
    return jnp.asarray(x.shape, dtype=jnp.int64)


@register("size_array")
def size_array(x):
    return jnp.asarray([x.size], dtype=jnp.int64)


# ------------------------------------------------------------------ indexing
@register("take", as_method=True)
def take(a, indices, axis=0, mode="clip"):
    idx = indices.astype(jnp.int32)
    if mode == "clip":
        idx = jnp.clip(idx, 0, a.shape[axis] - 1)
    elif mode == "wrap":
        idx = jnp.mod(idx, a.shape[axis])
    return jnp.take(a, idx, axis=axis)


@register("batch_take")
def batch_take(a, indices):
    idx = jnp.clip(indices.astype(jnp.int32), 0, a.shape[1] - 1)
    return jnp.take_along_axis(a, idx[:, None], axis=1)[:, 0]


@register("Embedding")
def Embedding(data, weight, input_dim=None, output_dim=None, dtype="float32",
              sparse_grad=False):
    """Embedding lookup (ref: src/operator/tensor/indexing_op.cc Embedding).
    Lowered to HLO Gather — the MXU-free path; the row-sparse gradient of the
    reference becomes a scatter-add which XLA emits for the vjp automatically."""
    idx = jnp.clip(data.astype(jnp.int32), 0, weight.shape[0] - 1)
    return jnp.take(weight, idx, axis=0)


@register("gather_nd")
def gather_nd(data, indices):
    """Ref: indexing_op.cc gather_nd. indices shape (M, ...) indexes the first M dims."""
    idx = indices.astype(jnp.int32)
    m = idx.shape[0]
    return data[tuple(idx[i] for i in range(m))]


@register("scatter_nd")
def scatter_nd(data, indices, shape=()):
    idx = indices.astype(jnp.int32)
    m = idx.shape[0]
    out = jnp.zeros(tuple(shape), dtype=data.dtype)
    return out.at[tuple(idx[i] for i in range(m))].set(data)


@register("_scatter_set_nd")
def _scatter_set_nd(lhs, indices, rhs, shape=()):
    idx = indices.astype(jnp.int32)
    m = idx.shape[0]
    return lhs.at[tuple(idx[i] for i in range(m))].set(rhs)


@register("one_hot", as_method=True)
def one_hot(indices, depth, on_value=1.0, off_value=0.0, dtype="float32"):
    from ..ndarray.ndarray import _as_jax_dtype
    oh = jax.nn.one_hot(indices.astype(jnp.int32), depth)
    return (oh * (on_value - off_value) + off_value).astype(_as_jax_dtype(dtype))


@register("_contrib_index_copy", aliases=("index_copy",))
def index_copy(old, index, new):
    """Ref: src/operator/contrib/index_copy.cc."""
    return old.at[index.astype(jnp.int32)].set(new)


# ------------------------------------------------------------------ ordering
@register("sort", as_method=True)
def sort(x, axis=-1, is_ascend=True):
    y = jnp.sort(x, axis=axis)
    return y if is_ascend else jnp.flip(y, axis=axis)


@register("argsort", as_method=True)
def argsort(x, axis=-1, is_ascend=True, dtype="float32"):
    idx = jnp.argsort(x, axis=axis)
    if not is_ascend:
        idx = jnp.flip(idx, axis=axis)
    return idx.astype(jnp.float32)


@register("topk", as_method=True)
def topk(x, axis=-1, k=1, ret_typ="indices", is_ascend=False, dtype="float32"):
    """Ref: src/operator/tensor/ordering_op.cc TopK. On TPU lowered to HLO Sort/TopK."""
    xm = jnp.moveaxis(x, axis, -1)
    if is_ascend:
        vals, idx_int = jax.lax.top_k(-xm, k)
        vals = -vals
    else:
        vals, idx_int = jax.lax.top_k(xm, k)
    if ret_typ == "mask":
        mask = jnp.sum(jax.nn.one_hot(idx_int, xm.shape[-1]), axis=-2)
        return jnp.moveaxis(mask, -1, axis)
    vals = jnp.moveaxis(vals, -1, axis)
    idx = jnp.moveaxis(idx_int, -1, axis).astype(jnp.float32)
    if ret_typ == "indices":
        return idx
    if ret_typ == "value":
        return vals
    if ret_typ == "both":
        return [vals, idx]
    raise ValueError("unknown ret_typ " + ret_typ)


# ------------------------------------------------------------------ dot
@register("dot", as_method=True)
def dot(lhs, rhs, transpose_a=False, transpose_b=False, forward_stype=None):
    """General dot (ref: src/operator/tensor/dot-inl.h). MXU-bound: contracts the
    last axis of lhs with the first of rhs (tensor-dot semantics for ndim>2)."""
    a = jnp.swapaxes(lhs, -1, -2) if transpose_a and lhs.ndim >= 2 else lhs
    b = jnp.swapaxes(rhs, -1, -2) if transpose_b and rhs.ndim >= 2 else rhs
    if transpose_a and lhs.ndim > 2:
        a = jnp.transpose(lhs, tuple(range(lhs.ndim))[::-1])
    if transpose_b and rhs.ndim > 2:
        b = jnp.transpose(rhs, tuple(range(rhs.ndim))[::-1])
    if a.ndim == 1 and b.ndim == 1:
        return contract_acc(jnp.dot, a, b)
    return contract_acc(jnp.tensordot, a, b, axes=([-1], [0]))


@register("batch_dot")
def batch_dot(lhs, rhs, transpose_a=False, transpose_b=False, forward_stype=None):
    a = jnp.swapaxes(lhs, -1, -2) if transpose_a else lhs
    b = jnp.swapaxes(rhs, -1, -2) if transpose_b else rhs
    return contract_acc(jnp.matmul, a, b)


@register("khatri_rao")
def khatri_rao(*args):
    """Column-wise Khatri-Rao product (ref: src/operator/contrib/krprod.cc)."""
    out = args[0]
    for m in args[1:]:
        out = jnp.einsum("ik,jk->ijk", out, m).reshape(-1, out.shape[1])
    return out


@register_param_shapes("Embedding")
def _embedding_param_shapes(shapes, attrs):
    """Weight=(input_dim, output_dim) regardless of data shape (ref:
    src/operator/tensor/indexing_op.h EmbeddingOpShape)."""
    return {1: (int(attrs["input_dim"]), int(attrs["output_dim"]))}


@register("reshape_like", as_method=True)
def reshape_like(lhs, rhs, lhs_begin=None, lhs_end=None, rhs_begin=None,
                 rhs_end=None):
    """Reshape lhs to rhs's shape, optionally only over a dim range
    (ref: src/operator/tensor/elemwise_unary_op_basic.cc reshape_like)."""
    if lhs_begin is None and rhs_begin is None:
        return jnp.reshape(lhs, rhs.shape)

    def _norm(v, ndim, default):
        # reference convention (matrix_op.cc ReshapeLikeParam): negative
        # indices mean ndim + v (so end=-1 is the last axis, NOT one-past)
        if v is None:
            return default
        return v + ndim if v < 0 else v

    lb = _norm(lhs_begin, lhs.ndim, 0)
    le = _norm(lhs_end, lhs.ndim, lhs.ndim)
    rb = _norm(rhs_begin, rhs.ndim, 0)
    re_ = _norm(rhs_end, rhs.ndim, rhs.ndim)
    tgt = lhs.shape[:lb] + rhs.shape[rb:re_] + lhs.shape[le:]
    return jnp.reshape(lhs, tgt)


@register("_ravel_multi_index", aliases=("ravel_multi_index",))
def _ravel_multi_index(data, shape=None):
    """(ndim, N) coordinates -> flat indices (ref: src/operator/tensor/
    ravel.cc). Row-major like the reference's RavelIndex kernel."""
    shape = tuple(int(s) for s in shape)
    idx = data.astype(jnp.int32)
    stride = 1
    strides = []
    for size in reversed(shape):
        strides.append(stride)
        stride *= size
    strides = strides[::-1]
    out = jnp.zeros(idx.shape[1:], jnp.int32)
    for d in range(len(shape)):
        out = out + idx[d] * strides[d]
    return out


@register("_unravel_index", aliases=("unravel_index",))
def _unravel_index(data, shape=None):
    """Flat indices -> (ndim, N) coordinates (ref: ravel.cc UnravelIndex)."""
    shape = tuple(int(s) for s in shape)
    flat = data.astype(jnp.int32)
    coords = []
    rem = flat
    for size in reversed(shape):
        coords.append(rem % size)
        rem = rem // size
    return jnp.stack(coords[::-1], axis=0)


@register("_contrib_getnnz", aliases=("getnnz",))
def getnnz(data, axis=None):
    """Count non-zeros (ref: src/operator/contrib/nnz.cc; the reference
    reads CSR metadata — here a dense reduction XLA fuses for free)."""
    nz = (data != 0)
    if axis is None:
        return jnp.sum(nz).astype(jnp.int32)
    return jnp.sum(nz, axis=axis).astype(jnp.int32)


@register("_contrib_SparseEmbedding", aliases=("SparseEmbedding",))
def SparseEmbedding(data, weight, input_dim=None, output_dim=None,
                    dtype="float32", deterministic=False):
    """Embedding whose gradient is row-sparse (ref: src/operator/tensor/
    indexing_op.cc SparseEmbedding). Same lowering as Embedding — the
    row-sparse gradient shape is an autograd-tape concern here
    (Parameter(sparse_grad=True)), not a separate kernel."""
    from .registry import get_op
    return get_op("Embedding").fn(data, weight, input_dim=input_dim,
                                  output_dim=output_dim, dtype=dtype)
