"""Reduction + broadcast-axis op family.

Reference: src/operator/tensor/broadcast_reduce_op_value.cc (+ broadcast_reduce-inl.cuh
hand-tiled CUDA reduction kernels). On TPU a reduction is a single HLO Reduce that XLA
tiles for the VPU, so the whole family is declarative here.

MXNet reduce semantics: ``axis`` may be int/tuple/None, ``keepdims`` bool, and
``exclude=True`` means "reduce over all axes NOT listed" (python/mxnet docs for sum).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register


def _norm_axis(axis, ndim, exclude=False):
    if axis is None:
        return None
    if isinstance(axis, int):
        axis = (axis,)
    axis = tuple(a % ndim for a in axis)
    if exclude:
        axis = tuple(a for a in range(ndim) if a not in axis)
    return axis


def _reduce(name, jfn, aliases=(), as_method=True):
    @register(name, aliases=aliases, as_method=as_method)
    def fn(x, axis=None, keepdims=False, exclude=False, **_ig):
        ax = _norm_axis(axis, x.ndim, exclude)
        return jfn(x, axis=ax, keepdims=keepdims)
    fn.__name__ = name
    return fn


sum_ = _reduce("sum", jnp.sum, aliases=("sum_axis",))
mean = _reduce("mean", jnp.mean)
prod = _reduce("prod", jnp.prod)
nansum = _reduce("nansum", jnp.nansum)
nanprod = _reduce("nanprod", jnp.nanprod)
max_ = _reduce("max", jnp.max, aliases=("max_axis",))
min_ = _reduce("min", jnp.min, aliases=("min_axis",))


@register("_square_sum", wrap=False)
def _square_sum(data, axis=None, keepdims=False, exclude=False, out=None, **_ig):
    """Sum of squares over an axis (ref: src/operator/tensor/square_sum.cc:50,
    square_sum-inl.h). Storage rule mirrors the reference's
    SquareSumForwardInferStorageType: a row_sparse input with axis=1 &
    keepdims=True yields a row_sparse output sharing the input's row ids
    (zero rows contribute zero); every other case is dense — for sparse
    input the stored values alone are reduced, so the dense logical shape
    never materializes."""
    from ..ndarray.ndarray import NDArray, _apply as _ap
    from ..ndarray.sparse import BaseSparseNDArray, RowSparseNDArray
    if isinstance(data, BaseSparseNDArray) and \
            not isinstance(data, RowSparseNDArray):
        # CSR: densify first (reference storage fallback) — the 1-D values
        # buffer is not axis-addressable
        data = data.todense()
    if isinstance(data, RowSparseNDArray):
        ax = _norm_axis(axis, len(data.shape), exclude)
        idx, shape = data._aux["indices"], data.shape
        if ax == (1,) and keepdims:
            vals = _ap(lambda v: jnp.sum(jnp.square(v), axis=1, keepdims=True),
                       (data,), name="_square_sum")
            res = RowSparseNDArray(vals._data, idx, (shape[0], 1))
            res._ag_entry = vals._ag_entry
        elif ax == (1,):
            res = _ap(lambda v: jnp.zeros((shape[0],), v.dtype)
                      .at[idx].add(jnp.sum(jnp.square(v), axis=1)),
                      (data,), name="_square_sum")
        elif ax == (0,):
            res = _ap(lambda v: jnp.sum(jnp.square(v), axis=0,
                                        keepdims=keepdims),
                      (data,), name="_square_sum")
        else:  # full reduction (axis=None or both axes)
            res = _ap(lambda v: jnp.sum(jnp.square(v), keepdims=keepdims),
                      (data,), name="_square_sum")
    else:
        ax = _norm_axis(axis, data.ndim if isinstance(data, NDArray)
                        else jnp.ndim(data), exclude)
        res = _ap(lambda v: jnp.sum(jnp.square(v), axis=ax, keepdims=keepdims),
                  (data,), name="_square_sum")
    if out is not None:
        return res.copyto(out)  # copyto moves sparse aux with the values
    return res


@register("norm", as_method=True)
def norm(x, ord=2, axis=None, keepdims=False, **_ig):  # noqa: A002
    """L1/L2 norm (ref: broadcast_reduce_op_value.cc norm)."""
    ax = _norm_axis(axis, x.ndim)
    if ord == 1:
        return jnp.sum(jnp.abs(x), axis=ax, keepdims=keepdims)
    return jnp.sqrt(jnp.sum(jnp.square(x), axis=ax, keepdims=keepdims))


@register("argmax", as_method=True)
def argmax(x, axis=None, keepdims=False):
    r = jnp.argmax(x, axis=axis, keepdims=keepdims).astype(jnp.float32)
    return r


@register("argmin", as_method=True)
def argmin(x, axis=None, keepdims=False):
    return jnp.argmin(x, axis=axis, keepdims=keepdims).astype(jnp.float32)


@register("argmax_channel")
def argmax_channel(x):
    """argmax over axis 1 (ref: broadcast_reduce_op_index.cc argmax_channel)."""
    return jnp.argmax(x, axis=1).astype(jnp.float32)


@register("broadcast_axis", aliases=("broadcast_axes",), as_method=True)
def broadcast_axis(x, axis=(), size=()):
    if isinstance(axis, int):
        axis, size = (axis,), (size,)
    shape = list(x.shape)
    for a, s in zip(axis, size):
        shape[a] = s
    return jnp.broadcast_to(x, tuple(shape))


@register("broadcast_to", as_method=False)
def broadcast_to(x, shape=()):
    # MXNet: 0 in target shape means "keep source dim"
    tgt = tuple(x.shape[i] if s == 0 else s for i, s in enumerate(shape))
    return jnp.broadcast_to(x, tgt)


@register("broadcast_like", as_method=False)
def broadcast_like(x, like):
    return jnp.broadcast_to(x, like.shape)


@register("pick", as_method=True)
def pick(x, index, axis=-1, keepdims=False, mode="clip"):
    """Pick per-row elements by index (ref: broadcast_reduce_op_index.cc pick)."""
    idx = index.astype(jnp.int32)
    if mode == "clip":
        idx = jnp.clip(idx, 0, x.shape[axis] - 1)
    else:
        idx = jnp.mod(idx, x.shape[axis])
    picked = jnp.take_along_axis(x, jnp.expand_dims(idx, axis=axis), axis=axis)
    if not keepdims:
        picked = jnp.squeeze(picked, axis=axis)
    return picked


@register("L2Normalization")
def L2Normalization(x, eps=1e-10, mode="instance"):
    """Ref: src/operator/l2_normalization.cc."""
    if mode == "instance":
        ax = tuple(range(1, x.ndim))
    elif mode == "channel":
        ax = (1,)
    elif mode == "spatial":
        ax = tuple(range(2, x.ndim))
    else:
        raise ValueError("unknown mode " + mode)
    return x / jnp.sqrt(jnp.sum(jnp.square(x), axis=ax, keepdims=True) + eps)


@register("softmax_cross_entropy")
def softmax_cross_entropy(data, label):
    """Fused CE (ref: src/operator/loss_binary_op.cc). Returns scalar sum."""
    logp = jax.nn.log_softmax(data, axis=-1)
    lab = label.astype(jnp.int32)
    picked = jnp.take_along_axis(logp, lab[:, None], axis=-1)
    return -jnp.sum(picked)
