"""CTC loss (ref: src/operator/nn/ctc_loss.cc + ctc_include/ warp-ctc).

The reference ships Baidu's warp-ctc CUDA/CPU kernels; here the alpha
(forward-variable) recursion of Graves et al. runs in the log semiring as a
``lax.scan`` over time — compiler-friendly static control flow, batched over
N on the VPU — and the gradient falls out of ``jax.vjp`` through the scan
(recompute-based, like every mxtpu op), replacing warp-ctc's hand-written
beta/backward kernel.

Semantics pinned to the reference implementation (ctc_loss-inl.h:120-200 —
note its code, not its docstring, which contradicts the code):

* input ``data`` is TNC (seq, batch, alphabet); softmax over C is applied
  internally (warp-ctc convention: raw activations in).
* ``blank_label='first'``: blank index 0, vocab tokens 1..C-1, label padding
  value 0. ``'last'``: blank C-1, tokens 0..C-2, padding -1
  (ctc_loss-inl.h:342).
* output: per-sample negative log likelihood, shape (N,).
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from .registry import register

_NEG = -1e30  # effective -inf that keeps logaddexp grads finite


def _ctc_nll(log_probs, labels, data_lengths, label_lengths, blank):
    """Batched CTC negative log likelihood.

    log_probs: [T, N, C] log-softmax outputs (f32).
    labels:    [N, L] int32 class ids (garbage beyond label_lengths is fine).
    data_lengths:  [N] int32, label_lengths: [N] int32.
    """
    T, N, C = log_probs.shape
    L = labels.shape[1]
    S = 2 * L + 1

    # extended sequence z[s]: blanks at even s, labels at odd s
    s_idx = jnp.arange(S)
    lab_idx = jnp.clip((s_idx - 1) // 2, 0, L - 1)
    z = jnp.where(s_idx % 2 == 1, labels[:, lab_idx], blank)       # [N, S]
    z = jnp.clip(z, 0, C - 1)  # padded labels may hold -1 etc.
    # skip transition s-2 -> s allowed when z[s] is a non-blank that differs
    # from z[s-2] (standard CTC topology)
    z_prev2 = jnp.pad(z, ((0, 0), (2, 0)), constant_values=blank)[:, :S]
    allow_skip = (s_idx % 2 == 1) & (z != z_prev2)                 # [N, S]

    def emit(t):
        return jnp.take_along_axis(log_probs[t], z, axis=1)        # [N, S]

    alpha0 = jnp.full((N, S), _NEG, jnp.float32)
    alpha0 = alpha0.at[:, 0].set(0.0)
    has_label = label_lengths > 0
    alpha0 = alpha0.at[:, 1].set(jnp.where(has_label, 0.0, _NEG))
    alpha0 = alpha0 + emit(0)

    def step_fn(alpha, t):
        a1 = jnp.pad(alpha, ((0, 0), (1, 0)), constant_values=_NEG)[:, :S]
        a2 = jnp.pad(alpha, ((0, 0), (2, 0)), constant_values=_NEG)[:, :S]
        new = jnp.logaddexp(alpha, a1)
        new = jnp.where(allow_skip, jnp.logaddexp(new, a2), new)
        new = new + emit(t)
        # past a sample's data length the forward variable is frozen so the
        # readout below sees alpha at exactly t = T_n - 1
        new = jnp.where((t < data_lengths)[:, None], new, alpha)
        return new, None

    alpha, _ = lax.scan(step_fn, alpha0, jnp.arange(1, T))

    rows = jnp.arange(N)
    end = 2 * label_lengths                                        # [N]
    ll_blank = alpha[rows, end]
    ll_label = jnp.where(has_label,
                         alpha[rows, jnp.maximum(end - 1, 0)], _NEG)
    return -jnp.logaddexp(ll_blank, ll_label)


@register("CTCLoss", aliases=("ctc_loss", "_contrib_CTCLoss", "_contrib_ctc_loss"))
def CTCLoss(data, label, data_lengths=None, label_lengths=None,
            use_data_lengths=False, use_label_lengths=False,
            blank_label="first"):
    """Connectionist temporal classification loss (ref: ctc_loss.cc).

    data: (T, N, C) raw activations; label: (N, L) padded class ids.
    Returns (N,) negative log likelihoods.
    """
    T, N, C = data.shape
    log_probs = jnp.asarray(data, jnp.float32)
    log_probs = log_probs - lax.stop_gradient(
        jnp.max(log_probs, axis=2, keepdims=True))
    log_probs = log_probs - jnp.log(
        jnp.sum(jnp.exp(log_probs), axis=2, keepdims=True))

    labels = jnp.asarray(label, jnp.int32)
    blank = 0 if blank_label == "first" else C - 1
    pad_value = 0 if blank_label == "first" else -1

    if use_data_lengths and data_lengths is not None:
        dlen = jnp.asarray(data_lengths, jnp.int32)
    else:
        dlen = jnp.full((N,), T, jnp.int32)
    if use_label_lengths and label_lengths is not None:
        llen = jnp.asarray(label_lengths, jnp.int32)
    else:
        # length = position of first padding value (ctc_loss-inl.h:138)
        is_pad = labels == pad_value
        llen = jnp.where(jnp.any(is_pad, axis=1),
                         jnp.argmax(is_pad, axis=1),
                         labels.shape[1]).astype(jnp.int32)

    return _ctc_nll(log_probs, labels, dlen, llen, blank).astype(data.dtype)
