"""The `Custom` registry op (ref: src/operator/custom/custom.cc — Custom is
a real NNVM op whose attrs name a python-registered prop). Registering here,
inside the ops import chain, puts it in the mx.nd / mx.sym namespaces like
every other op; the callback machinery lives in mxtpu/operator.py.
"""
from .registry import register


@register("Custom")
def Custom(*data, op_type=None, **attrs):
    from .. import operator as _operator
    return _operator._invoke(op_type, data, attrs)
