"""Pallas TPU kernels for hot ops (SURVEY §7 stage 8).

The reference's answer to hot-spot ops was hand-written CUDA (cudnn wrappers,
fused rnn_impl.h, attention helpers); here the escape hatch below XLA is
Pallas. Kernels fall back to pure-XLA implementations when shapes or platform
don't fit, so numerics are always available on CPU test runs.
"""
from .conv import fused_conv
from .flash_attention import flash_attention

__all__ = ["flash_attention", "fused_conv"]
