"""Flash attention: fused online-softmax attention for TPU.

No reference counterpart (the reference predates flash attention; its only
attention helper is ``_contrib_div_sqrt_dim``, src/operator/contrib/
transformer.cc). This is the single-chip hot path under
:func:`mxtpu.parallel.ring_attention.ring_self_attention`'s per-shard compute
and the model zoo transformer.

Design (TPU-first):
* forward: one Pallas kernel, grid (batch*heads, Tq/bq, Tk/bk) — the k-block
  axis is innermost so the online-softmax state (m, l, acc) lives in VMEM
  scratch across k steps; the [T, T] score matrix never materializes in HBM.
  Causal q/k block pairs above the diagonal are skipped (`pl.when`), saving
  ~half the FLOPs.
* backward: custom_vjp recomputes probabilities blockwise from the saved
  log-sum-exp via ``lax.scan`` over k-blocks (flash-attention-2 equations) —
  memory stays O(T*D), no Pallas needed since the MXU work is plain matmuls
  XLA already schedules well.
* fallback: non-TPU platforms or non-divisible shapes use the XLA softmax
  path with the same signature. Why each fallback happened is counted in
  the reason-tagged ``pallas_flash.{pallas,xla,fallback}`` telemetry
  family (the conv kernel's dispatch-stats discipline).
* parity off-chip: ``MXTPU_FLASH_INTERPRET=1`` runs the kernel through
  the Pallas interpreter, so tier-1 pins the real online-softmax kernel
  against the XLA path on CPU without a chip (and the autotuner can
  measure block plans on the host tier).
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from . import autotune

_NEG_INF = -1e30


def _interpret():
    """MXTPU_FLASH_INTERPRET=1 runs the kernel via the Pallas interpreter
    on any platform — the tier-1 parity path (CPU, no chip). Trace-time,
    so it rides policy_key like every other lever."""
    return os.environ.get("MXTPU_FLASH_INTERPRET", "0") == "1"


# observability: how often the hand kernel ran vs why it fell back — the
# same dict-shaped view over the telemetry registry conv.py exposes, so
# bench/report/JSONL read one copy of the truth.
class _DispatchStatsView:
    """Read-only dict-shaped view over the telemetry counters."""

    _KEYS = ("pallas", "xla", "fallback_reasons")

    def __getitem__(self, key):
        from ... import telemetry
        if key == "fallback_reasons":
            return telemetry.tagged("pallas_flash.fallback")
        if key not in self._KEYS:
            raise KeyError(key)
        return int(telemetry.value("pallas_flash." + key))

    def get(self, key, default=None):
        try:
            return self[key]
        except KeyError:
            return default

    def __iter__(self):
        return iter(self._KEYS)

    def __len__(self):
        return len(self._KEYS)

    def keys(self):
        return list(self._KEYS)

    def items(self):
        return [(k, self[k]) for k in self._KEYS]

    def __repr__(self):
        return repr(dict(self.items()))


DISPATCH_STATS = _DispatchStatsView()


def reset_dispatch_stats():
    from ... import telemetry
    telemetry.reset_metric("pallas_flash.pallas")
    telemetry.reset_metric("pallas_flash.xla")
    telemetry.reset_metric("pallas_flash.fallback")


def _count_fallback(reason):
    from ... import telemetry
    telemetry.inc("pallas_flash.xla")
    telemetry.inc("pallas_flash.fallback", tag=reason)


def _xla_attention(q, k, v, causal, scale):
    out, _ = _xla_attention_lse(q, k, v, causal, scale)
    return out


def _xla_attention_lse(q, k, v, causal, scale):
    """Fallback attention returning (out, lse) — ONE copy of the XLA math
    (softmax(s) == exp(s - lse) exactly); differentiable directly."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32),
                   preferred_element_type=jnp.float32) * scale
    if causal:
        tq, tk = s.shape[-2:]
        mask = jnp.arange(tq)[:, None] >= jnp.arange(tk)[None, :]
        s = jnp.where(mask[None, None], s, _NEG_INF)
    lse = jax.scipy.special.logsumexp(s, axis=-1)
    p = jnp.exp(s - lse[..., None])
    out = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32),
                     preferred_element_type=jnp.float32)
    return out.astype(q.dtype), lse


def _fa_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
               *, scale, causal, block_q, block_k, n_k):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # causal: a k block strictly above the q block's diagonal is all-masked
    run = (not causal) or (ki * block_k <= qi * block_q + block_q - 1)

    @pl.when(run)
    def _compute():
        # operands stay in their input dtype (bf16 = single-pass MXU);
        # accumulation is f32 via preferred_element_type. K arrives
        # pre-transposed [d, bk] so both matmuls are plain (1,0)
        # contractions (Mosaic's native MXU form).
        q = q_ref[0]                              # [bq, d]
        kt = k_ref[0]                             # [d, bk]
        vb = v_ref[0]                             # [bk, d]
        # bf16 inputs: single-pass MXU (DEFAULT) — the global
        # jax_default_matmul_precision=float32 would request a multi-pass
        # bf16 contraction Mosaic cannot lower. f32 inputs keep HIGHEST so
        # reference-parity numerics hold.
        prec = (jax.lax.Precision.HIGHEST if q.dtype == jnp.float32
                else jax.lax.Precision.DEFAULT)
        s = jax.lax.dot_general(q, kt, (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32,
                                precision=prec) * scale
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        m_prev = m_scr[:, :1]                     # [bq, 1]
        l_prev = l_scr[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                    # [bq, bk]
        alpha = jnp.exp(m_prev - m_new)           # [bq, 1]
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        # p cast to the value dtype for a single-pass MXU matmul (standard
        # flash practice); accumulator stays f32
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(vb.dtype), vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=prec)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ki == n_k - 1)
    def _finalize():
        l = l_scr[:, :1]
        o_ref[0] = (acc_scr[:] / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
        # [bq, 128] lane-replicated (TPU tiling needs a 128 trailing dim);
        # lane 0 is sliced out on the host side
        lse_ref[0] = jnp.broadcast_to(
            m_scr[:, :1] + jnp.log(jnp.maximum(l, 1e-30)), lse_ref.shape[1:])


def _fa_forward_pallas(q, k, v, causal, scale, block_q, block_k):
    b, h, t, d = q.shape
    tk = k.shape[2]
    bh = b * h
    q3 = q.reshape(bh, t, d)
    k3 = jnp.swapaxes(k.reshape(bh, tk, d), 1, 2)  # [bh, d, tk] for the MXU
    v3 = v.reshape(bh, tk, d)
    n_q = t // block_q
    n_k = tk // block_k
    from jax.experimental.pallas import tpu as pltpu
    kernel = functools.partial(_fa_kernel, scale=scale, causal=causal,
                               block_q=block_q, block_k=block_k, n_k=n_k)
    interpret = _interpret()
    extra = {}
    if not interpret:
        # jax 0.4.37 renamed CompilerParams -> TPUCompilerParams; the
        # interpreter needs neither (Mosaic-only hint)
        cp = (getattr(pltpu, "CompilerParams", None)
              or pltpu.TPUCompilerParams)
        extra["compiler_params"] = cp(
            dimension_semantics=("parallel", "parallel", "arbitrary"))
    out, lse = pl.pallas_call(
        kernel,
        grid=(bh, n_q, n_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b_, i, j: (b_, i, 0)),
            pl.BlockSpec((1, d, block_k), lambda b_, i, j: (b_, 0, j)),
            pl.BlockSpec((1, block_k, d), lambda b_, i, j: (b_, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b_, i, j: (b_, i, 0)),
            pl.BlockSpec((1, block_q, 128), lambda b_, i, j: (b_, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, d), q.dtype),
            jax.ShapeDtypeStruct((bh, t, 128), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),  # running max m
            pltpu.VMEM((block_q, 128), jnp.float32),  # running sum l
            pltpu.VMEM((block_q, d), jnp.float32),    # output accumulator
        ],
        interpret=interpret,
        **extra,
    )(q3, k3, v3)
    return out.reshape(b, h, t, d), lse[:, :, 0].reshape(b, h, t)


def _fa_backward_blockwise(q, k, v, out, lse, g, causal, scale, block_k,
                           g_lse=None):
    """Flash-attention-2 backward, blockwise over k in plain jax:
    P = exp(S - lse); dv = P^T g; ds = P * (g v^T - D); dq += ds k; dk += ds^T q.

    ``g_lse`` is the cotangent of the lse OUTPUT (flash_attention_with_lse;
    d lse_i / d s_ik = P_ik, so it adds ``P * g_lse`` to ds).
    """
    f32 = jnp.float32
    q32, k32, v32 = q.astype(f32), k.astype(f32), v.astype(f32)
    g32, out32 = g.astype(f32), out.astype(f32)
    t, tk = q.shape[2], k.shape[2]
    delta = jnp.sum(out32 * g32, axis=-1)            # [b, h, t]
    if g_lse is not None:
        # fold the lse cotangent into the per-row constant: ds = P * (dP
        # - delta + g_lse), same row-broadcast shape as delta
        delta = delta - g_lse.astype(f32)
    n_k = tk // block_k
    q_pos = jnp.arange(t)

    def body(dq_acc, j):
        ks = jax.lax.dynamic_slice_in_dim(k32, j * block_k, block_k, axis=2)
        vs = jax.lax.dynamic_slice_in_dim(v32, j * block_k, block_k, axis=2)
        s = jnp.einsum("bhqd,bhkd->bhqk", q32, ks,
                       preferred_element_type=f32) * scale
        if causal:
            k_pos = j * block_k + jnp.arange(block_k)
            mask = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(mask[None, None], s, _NEG_INF)
        p = jnp.exp(s - lse[..., None])              # [b,h,t,bk]
        dv = jnp.einsum("bhqk,bhqd->bhkd", p, g32,
                        preferred_element_type=f32)
        dp = jnp.einsum("bhqd,bhkd->bhqk", g32, vs,
                        preferred_element_type=f32)
        ds = p * (dp - delta[..., None]) * scale
        dq_acc = dq_acc + jnp.einsum("bhqk,bhkd->bhqd", ds, ks,
                                     preferred_element_type=f32)
        dk = jnp.einsum("bhqk,bhqd->bhkd", ds, q32,
                        preferred_element_type=f32)
        return dq_acc, (dk, dv)

    dq, (dk_blocks, dv_blocks) = jax.lax.scan(
        body, jnp.zeros_like(q32), jnp.arange(n_k))
    # scan stacks [n_k, b, h, bk, d] -> [b, h, tk, d]
    dk = jnp.moveaxis(dk_blocks, 0, 2).reshape(k.shape)
    dv = jnp.moveaxis(dv_blocks, 0, 2).reshape(v.shape)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


def _platform():
    try:
        return jax.devices()[0].platform
    except Exception:  # noqa: BLE001
        return "unknown"


def _pick_block(n, want, mult):
    """Largest block ≤ want that is a multiple of ``mult`` and divides n —
    so sequence lengths like 768 or 1536 (not divisible by the default 512)
    still get a Pallas kernel instead of silently falling back. A ``want``
    below the hardware granule rounds UP to ``mult`` (a user asking for
    block_k=64 should get the 128-lane kernel, not the fallback)."""
    b = min(want, n)
    b -= b % mult
    if b == 0 and n >= mult:
        b = mult
    while b >= mult:
        if n % b == 0:
            return b
        b -= mult
    return None


_warned_fallbacks = set()


def shape_class_of(q, k):
    """The autotuner's shape class for this attention call: problem
    geometry + dtype. Causal is deliberately absent — the block plan is
    launch geometry, and a plan that wins on the full score grid also
    serves the causal-skip variant of the same shape. Works on tracers
    (shape/dtype only)."""
    b, h, t, d = q.shape
    return {"b": int(b), "h": int(h), "t": int(t),
            "tk": int(k.shape[2]), "d": int(d),
            "dtype": jnp.dtype(q.dtype).name}


def _resolve_blocks(q, k, block_q, block_k):
    """(block_q, block_k) for the Pallas kernel, or None → XLA fallback.

    On TPU the fallback is a real memory cliff (the [T, T] score matrix
    materializes in HBM), so it warns ONCE per offending shape instead of
    silently absorbing it (VERDICT r4 weak #7). Every outcome is counted
    in ``pallas_flash.{pallas,xla}`` / reason-tagged
    ``pallas_flash.fallback``. A tuned plan (autotune.lookup) may
    override the q/k block wants, but only after revalidating against
    the SAME granule/divisor gates — a stale artifact degrades to the
    defaults with a counted drop."""
    t, tk, d = q.shape[2], k.shape[2], q.shape[3]
    on_tpu = _platform() == "tpu"
    from ... import telemetry

    def _fallback(reason):
        _count_fallback(reason)
        if on_tpu:
            key = (reason, t, tk, d)
            if key not in _warned_fallbacks:
                _warned_fallbacks.add(key)
                import warnings
                warnings.warn(
                    "flash_attention falling back to the XLA softmax path "
                    "(%s; q[T=%d] k[T=%d] D=%d): the [T,T] score matrix "
                    "will materialize in HBM — pad T to a multiple of 8 "
                    "(q) / 128 (k) for the fused kernel (head dims are "
                    "padded to the 128-lane granule automatically)"
                    % (reason, t, tk, d))
        return None

    if not on_tpu and not _interpret():
        # expected off-TPU; counted but not a cliff worth warning about
        return _fallback("platform is not tpu")
    # head dims off the 128-lane granule (64 for BERT-base et al.) are
    # zero-padded to the next multiple by _pad_head_dim — scores and lse
    # are invariant to zero columns, so no fallback needed.
    # MXTPU_FLASH_PAD_D=0 restores the old fallback (perf A/B only).
    # default mirrors the registry.policy_key entry — a bare .get() here
    # would alias unset (None) and "1" onto one compiled-cache key
    if d % 128 != 0 and os.environ.get("MXTPU_FLASH_PAD_D", "1") == "0":
        return _fallback("head dim not a multiple of 128 (padding "
                         "disabled by MXTPU_FLASH_PAD_D=0)")
    tuned = autotune.lookup("pallas_flash", shape_class_of(q, k))
    if tuned is not None:
        tbq = int(tuned.get("block_q", 0))
        tbk = int(tuned.get("block_k", 0))
        if (_pick_block(t, tbq, 8) == tbq
                and _pick_block(tk, tbk, 128) == tbk):
            block_q, block_k = tbq, tbk
        else:
            autotune.plan_infeasible("pallas_flash")
    bq = _pick_block(t, block_q, 8)       # sublane granularity
    bk = _pick_block(tk, block_k, 128)    # lane granularity
    if bq is None or bk is None:
        return _fallback("sequence length has no TPU-tileable block")
    telemetry.inc("pallas_flash.pallas")
    return bq, bk


def _pad_head_dim(q, k, v):
    """Zero-pad [B, H, T, D] operands to the 128-lane granule. Zero key/
    query columns contribute nothing to scores and zero value columns are
    sliced off the output, so attention is exact under this padding."""
    d = q.shape[-1]
    d_pad = -(-d // 128) * 128
    if d_pad == d:
        return q, k, v, d
    pad = [(0, 0)] * 3 + [(0, d_pad - d)]
    return jnp.pad(q, pad), jnp.pad(k, pad), jnp.pad(v, pad), d


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal=False, scale=None, block_q=512,
                    block_k=512):
    """Fused attention [B, H, T, D] -> [B, H, T, D]; falls back to XLA softmax
    off-TPU or for non-divisible shapes."""
    out, _ = _fa_fwd(q, k, v, causal, scale, block_q, block_k)
    return out


def _fa_fwd(q, k, v, causal, scale, block_q, block_k):
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    blocks = _resolve_blocks(q, k, block_q, block_k)
    if blocks is None:
        out = _xla_attention(q, k, v, causal, scale)
        return out, (q, k, v, out, None)
    qp, kp, vp, d = _pad_head_dim(q, k, v)
    out, lse = _fa_forward_pallas(qp, kp, vp, causal, scale, *blocks)
    if qp is not q:
        out = out[..., :d]
    return out, (q, k, v, out, lse)


def _fa_bwd(causal, scale, block_q, block_k, res, g):
    q, k, v, out, lse = res
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    # backward is plain jax (no lane constraint) but its k-block must
    # DIVIDE tk — the scan would silently drop a ragged tail otherwise
    block_k = _pick_block(k.shape[2], block_k, 1) or k.shape[2]
    if lse is None:
        # fallback path: differentiate the XLA implementation directly
        _, vjp = jax.vjp(lambda q_, k_, v_:
                         _xla_attention(q_, k_, v_, causal, scale), q, k, v)
        return vjp(g)
    return _fa_backward_blockwise(q, k, v, out, lse, g, causal, scale,
                                  block_k)


flash_attention.defvjp(_fa_fwd, _fa_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention_with_lse(q, k, v, causal=False, scale=None, block_q=512,
                             block_k=512):
    """Like :func:`flash_attention` but ALSO returns the per-row
    log-sum-exp [B, H, T] — the quantity that lets partial attention
    results over disjoint key sets be merged exactly (ring attention's
    per-step blocks combine as out = Σ_j softmax(lse_j) out_j)."""
    out, lse, _res = _fa_lse_fwd_impl(q, k, v, causal, scale, block_q,
                                      block_k)
    return out, lse


def _fa_lse_fwd_impl(q, k, v, causal, scale, block_q, block_k):
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    blocks = _resolve_blocks(q, k, block_q, block_k)
    if blocks is None:
        out, lse = _xla_attention_lse(q, k, v, causal, scale)
        return out, lse, (q, k, v, out, None)
    qp, kp, vp, d = _pad_head_dim(q, k, v)
    out, lse = _fa_forward_pallas(qp, kp, vp, causal, scale, *blocks)
    if qp is not q:
        out = out[..., :d]
    return out, lse, (q, k, v, out, lse)


def _fa_lse_fwd(q, k, v, causal, scale, block_q, block_k):
    out, lse, res = _fa_lse_fwd_impl(q, k, v, causal, scale, block_q,
                                     block_k)
    return (out, lse), res


def _fa_lse_bwd(causal, scale, block_q, block_k, res, cots):
    g, g_lse = cots
    q, k, v, out, lse = res
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    block_k = _pick_block(k.shape[2], block_k, 1) or k.shape[2]
    if lse is None:
        _, vjp = jax.vjp(lambda q_, k_, v_:
                         _xla_attention_lse(q_, k_, v_, causal, scale),
                         q, k, v)
        return vjp((g, g_lse))
    return _fa_backward_blockwise(q, k, v, out, lse, g, causal, scale,
                                  block_k, g_lse=g_lse)


flash_attention_with_lse.defvjp(_fa_lse_fwd, _fa_lse_bwd)


# ------------------------------------------------------- autotune descriptor
# candidate q/k block wants the space sweeps; each realizes through
# _pick_block (8-sublane / 128-lane granules), so every emitted plan is a
# block pair the kernel can actually launch
_TUNE_WANTS = (128, 256, 512, 1024, 2048)
# VMEM the feasibility gate lets a candidate plan for (same headroom
# philosophy as conv's _VMEM_BUDGET; flash has no serving-side VMEM gate
# because its default blocks are bounded, but the tuner's space is not)
_TUNE_VMEM_BUDGET = 10 * 1024 * 1024


def _tune_space(sc):
    plans = []
    for wq in _TUNE_WANTS:
        for wk in _TUNE_WANTS:
            bq = _pick_block(sc["t"], wq, 8)
            bk = _pick_block(sc["tk"], wk, 128)
            if bq is not None and bk is not None:
                plans.append({"block_q": bq, "block_k": bk})
    return plans


def _tune_default(sc):
    return {"block_q": _pick_block(sc["t"], 512, 8),
            "block_k": _pick_block(sc["tk"], 512, 128)}


def _tune_vmem(bq, bk, d, itm):
    dp = -(-d // 128) * 128
    return (2 * (bq * dp + dp * bk + bk * dp) * itm  # q/kT/v blocks (dbuf)
            + bq * bk * 4                            # score/p tile (f32)
            + 2 * bq * 128 * 4 + bq * dp * 4         # m, l, acc scratch
            + 2 * (bq * dp * itm + bq * 128 * 4))    # out + lse tiles


def _tune_feasible(plan, sc):
    bq = int(plan.get("block_q", 0))
    bk = int(plan.get("block_k", 0))
    if _pick_block(sc["t"], bq, 8) != bq:
        return False, ("block_q=%d is not an 8-multiple divisor of t=%d"
                       % (bq, sc["t"]))
    if _pick_block(sc["tk"], bk, 128) != bk:
        return False, ("block_k=%d is not a 128-multiple divisor of tk=%d"
                       % (bk, sc["tk"]))
    itm = jnp.dtype(sc["dtype"]).itemsize
    vmem = _tune_vmem(bq, bk, sc["d"], itm)
    if vmem > _TUNE_VMEM_BUDGET:
        return False, ("VMEM budget: %dx%d blocks need ~%.1f MB > %.1f MB"
                       % (bq, bk, vmem / 2**20,
                          _TUNE_VMEM_BUDGET / 2**20))
    return True, None


def _tune_runner(sc):
    """Real buffers + a dispatch through flash_attention's public entry.
    causal=False times the full score grid — the plan also serves the
    causal variant of the shape class (see shape_class_of)."""
    import numpy as np
    rng = np.random.default_rng(0)
    dt = jnp.dtype(sc["dtype"])
    shp_q = (sc["b"], sc["h"], sc["t"], sc["d"])
    shp_k = (sc["b"], sc["h"], sc["tk"], sc["d"])
    q = jnp.asarray(rng.standard_normal(shp_q), dt)
    k = jnp.asarray(rng.standard_normal(shp_k), dt)
    v = jnp.asarray(rng.standard_normal(shp_k), dt)

    def fn(q_, k_, v_):
        return flash_attention(q_, k_, v_, causal=False)

    return fn, (q, k, v)


def _tune_classes(host_tier):
    """Representative shape classes a tuning session sweeps. The host
    tier shrinks batch/heads/T so interpret-mode candidates stay inside
    the perf-battery budget; on a chip the bench-transformer shapes run
    as-is."""
    if host_tier:
        shapes = [(1, 2, 256, 256, 64), (1, 2, 512, 512, 64)]
    else:
        shapes = [(4, 8, 512, 512, 64), (2, 8, 1024, 1024, 128),
                  (2, 8, 2048, 2048, 128)]
    return [{"b": b, "h": h, "t": t, "tk": tk, "d": d, "dtype": "float32"}
            for (b, h, t, tk, d) in shapes]


autotune.register_kernel(autotune.TunableKernel(
    kernel_id="pallas_flash",
    space=_tune_space,
    default=_tune_default,
    feasible=_tune_feasible,
    runner=_tune_runner,
    classes=_tune_classes,
    interpret_env="MXTPU_FLASH_INTERPRET",
))
