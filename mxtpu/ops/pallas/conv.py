"""Pallas fused implicit-GEMM convolution for the small-K early conv stages.

Why this kernel exists (PERF.md round-5 attribution): stem + stage2 of the
bench ResNet-50 consume ~78% of the train step (39.7 of 50.6 ms fwd+bwd)
while holding ~15% of the FLOPs — the 7x7s2 stem measures ~3 TFLOP/s and
the 1x1 bottleneck pointwise convs ~3.1-3.3 TFLOP/s against the 93-135
TFLOP/s the same chip sustains on well-shaped contractions. These convs
underfill the MXU on at least one side (im2col K = kh*kw*C_in, or C_out,
below the 128-lane granule), and XLA's generic conv lowering leaves the
gap on the table. The hand kernel turns the conv into the implicit GEMM
XLA won't form and keeps the epilogue (BN one-pass affine, ReLU, residual
add) in VMEM instead of round-tripping HBM between ops.

Design (mirrors flash_attention.py):

* forward: ONE Pallas kernel. The input is phase-decomposed by the stride
  (a space-to-depth on the padded image: plane (p, q) holds rows ≡ p,
  cols ≡ q mod stride) so the kernel only ever takes *static stride-1
  slices*; output rows are tiled into halo-materialized row blocks so
  each grid step's VMEM block is small and offsets stay block-aligned.
  Per grid step the kernel accumulates kh*kw MXU contractions
  [bo*OW, C_in] x [C_in, C_out] into an f32 accumulator, then applies the
  fused epilogue (scale, bias, residual, ReLU) and writes the output tile
  once — conv + BN(affine) + ReLU + add in a single HBM pass.
* backward: ``jax.custom_vjp``, blockwise over the batch in plain jax
  (the flash_attention pattern — the MXU work is matmuls XLA already
  schedules well): dW = Σ_blocks im2col(x_b)^T @ dz_b and
  dX = col2im(dz_b @ W^T), with im2col/col2im expressed through the same
  phase decomposition (static slices + adds, no strided scatters). The
  SAME backward serves the Pallas and fallback forwards — the math is
  exact either way, so fwd AND bwd stay on the hand path.
* dispatch: ``conv_acc.conv_fast`` routes a conv here only when
  ``MXTPU_PALLAS_CONV`` is on AND the shape underfills the MXU
  (``pallas_applicable``); inside, ``_resolve`` may still fall back to
  the XLA conv (non-TPU platform, VMEM budget) with the reason recorded
  in ``DISPATCH_STATS`` — everything else never leaves the XLA path that
  already runs near ceiling. The lever is in ``registry.policy_key`` so
  0/1 A/B flips genuinely recompile.
* parity off-chip: ``MXTPU_PALLAS_CONV_INTERPRET=1`` runs the kernel
  through the Pallas interpreter, so tier-1 pins fwd + both grads against
  ``lax.conv_general_dilated`` on CPU without a chip.
"""
from __future__ import annotations

import functools
import os
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from . import autotune
from .flash_attention import _platform  # one platform resolver per package

__all__ = ["fused_conv", "pallas_applicable", "shape_class_of",
           "DISPATCH_STATS", "reset_dispatch_stats"]

_MXU_LANES = 128
# VMEM spend the forward kernel may plan for (input block double-buffered +
# f32 accumulator + output tile); v5e has ~16 MB/core and the pipeline
# needs headroom for double buffering, so plan well under it.
_VMEM_BUDGET = 10 * 1024 * 1024
# target GEMM M rows per grid step (a few MXU passes; keeps the f32
# accumulator tile small)
_TARGET_M = 2048
# im2col patches materialized per backward scan block (~32 MB)
_BWD_COLS_BUDGET = 32 << 20
_LOW = (jnp.bfloat16, jnp.float32)

# observability for tests and tools: how often the hand kernel actually
# ran vs why it fell back. The SOURCE OF TRUTH is the telemetry registry
# (``pallas_conv.pallas`` / ``pallas_conv.xla`` counters, reason-tagged
# ``pallas_conv.fallback``) so bench/report/JSONL all see one copy;
# this dict-shaped view keeps the original module-level surface alive
# for existing tests and tools.
class _DispatchStatsView:
    """Read-only dict-shaped view over the telemetry counters."""

    _KEYS = ("pallas", "xla", "fallback_reasons")

    def __getitem__(self, key):
        from ... import telemetry
        if key == "fallback_reasons":
            return telemetry.tagged("pallas_conv.fallback")
        if key not in self._KEYS:
            raise KeyError(key)
        return int(telemetry.value("pallas_conv." + key))

    def get(self, key, default=None):
        try:
            return self[key]
        except KeyError:
            return default

    def __iter__(self):
        return iter(self._KEYS)

    def __len__(self):
        return len(self._KEYS)

    def keys(self):
        return list(self._KEYS)

    def items(self):
        return [(k, self[k]) for k in self._KEYS]

    def __repr__(self):
        return repr(dict(self.items()))


DISPATCH_STATS = _DispatchStatsView()


def reset_dispatch_stats():
    from ... import telemetry
    telemetry.reset_metric("pallas_conv.pallas")
    telemetry.reset_metric("pallas_conv.xla")
    telemetry.reset_metric("pallas_conv.fallback")


def _interpret():
    """MXTPU_PALLAS_CONV_INTERPRET=1 runs the kernel via the Pallas
    interpreter on any platform — the tier-1 parity path (CPU, no chip).
    Trace-time, so it rides policy_key like every other lever."""
    return os.environ.get("MXTPU_PALLAS_CONV_INTERPRET", "0") == "1"


class _Cfg(NamedTuple):
    """Static conv config baked into the custom_vjp (hashable)."""
    strides: Tuple[int, int]
    padding: Tuple[Tuple[int, int], Tuple[int, int]]
    relu: bool
    has_scale: bool
    has_bias: bool
    has_residual: bool
    res_dtype: str = ""   # residual dtype name — saves the dtype, not the
    #                       tensor, in the vjp residuals (d_residual = g)


def _out_hw(size, lo, hi, k, s):
    return (size + lo + hi - k) // s + 1


def pallas_applicable(x, w, strides, padding, lhs_dilation, rhs_dilation,
                      dims, groups):
    """(True, None) when the conv is in the hand kernel's domain AND the
    shape underfills the MXU, else (False, reason). The shape gate is the
    PERF.md finding made executable: route only convs whose im2col K
    (= kh*kw*C_in) or C_out sits below the 128-lane granule — the 7x7s2
    stem (C_out=64), the 1x1 bottleneck pointwise convs (K or C_out = 64),
    the stage-2 small-C spatials — and leave large-K convs (both sides
    >= 128) on the XLA path that already runs near the conv-stack
    ceiling."""
    if dims != ("NHWC", "HWIO", "NHWC"):
        return False, "layout not NHWC/HWIO"
    if x.ndim != 4:
        return False, "not a 2D conv"
    if int(groups) != 1:
        return False, "grouped conv"
    if tuple(lhs_dilation) != (1, 1):
        return False, "lhs dilation (transposed conv)"
    if tuple(rhs_dilation) != (1, 1):
        return False, "rhs dilation"
    if x.dtype not in _LOW or w.dtype not in _LOW:
        return False, "dtype not f32/bf16"
    if x.dtype != w.dtype:
        # lax.conv_general_dilated rejects mixed operands; the kernel's
        # dot_general would silently promote — the lever must not change
        # which programs are valid
        return False, "mixed operand dtypes"
    if any(p < 0 for pair in padding for p in pair):
        return False, "negative padding"
    kh, kw, cin, cout = w.shape
    k_im2col = kh * kw * cin
    if k_im2col >= _MXU_LANES and cout >= _MXU_LANES:
        return False, ("MXU-filled shape (K=%d, C_out=%d): XLA path is "
                       "already near ceiling" % (k_im2col, cout))
    sh, sw = tuple(strides)
    (plo, phi), (qlo, qhi) = (tuple(p) for p in padding)
    oh = _out_hw(x.shape[1], plo, phi, kh, sh)
    ow = _out_hw(x.shape[2], qlo, qhi, kw, sw)
    if oh < 1 or ow < 1:
        return False, "degenerate output"
    return True, None


def _count_fallback(reason):
    from ... import telemetry
    telemetry.inc("pallas_conv.xla")
    telemetry.inc("pallas_conv.fallback", tag=reason)


def _divisor_block(n, want):
    """Largest divisor of n that is <= max(want, 1)."""
    b = max(min(want, n), 1)
    while n % b:
        b -= 1
    return b


def _lane_pad(c):
    return -(-c // _MXU_LANES) * _MXU_LANES


def _plan_vmem(bo, oh, ow, cin, cout, kh, kw, sh, sw, itm, has_scale,
               has_residual):
    """VMEM bytes the forward kernel plans for at row-block ``bo``: the
    pipelined working set — double-buffered input block + the resident
    whole-weight block (the gate allows C_out<128 at ANY C_in, so a
    fat-C_in kernel must fall back here, not die in Mosaic) + output
    tile (+ residual tile, + f32 conv_raw tile when the affine epilogue
    saves it) + the f32 accumulator across the contractions. Shared by
    trace-time _resolve and the autotuner's pre-compile feasibility
    gate, so a tuned plan can never admit geometry _resolve would
    reject."""
    bo_in = bo + (kh - 1) // sh
    ws = ow + (kw - 1) // sw
    return (2 * sh * sw * bo_in * ws * _lane_pad(cin) * itm
            + kh * kw * max(cin, 8) * _lane_pad(cout) * itm
            + 2 * bo * ow * _lane_pad(cout) * itm
            + (2 * bo * ow * _lane_pad(cout) * itm if has_residual
               else 0)
            + (2 * bo * ow * _lane_pad(cout) * 4 if has_scale else 0)
            + bo * ow * _lane_pad(cout) * 4)


def shape_class_of(x, w, cfg):
    """The autotuner's shape class for this conv: full launch geometry +
    dtype + the epilogue flags that change the VMEM plan. Works on
    tracers (shape/dtype only)."""
    n, h, wd, cin = x.shape
    kh, kw, _, cout = w.shape
    return {"n": int(n), "h": int(h), "w": int(wd), "cin": int(cin),
            "kh": int(kh), "kw": int(kw), "cout": int(cout),
            "sh": cfg.strides[0], "sw": cfg.strides[1],
            "p0": cfg.padding[0][0], "p1": cfg.padding[0][1],
            "q0": cfg.padding[1][0], "q1": cfg.padding[1][1],
            "dtype": jnp.dtype(x.dtype).name,
            "scale": int(cfg.has_scale), "res": int(cfg.has_residual)}


def _resolve(x, w, cfg):
    """Kernel launch geometry (bo = output rows per grid step) or
    (None, reason) -> XLA fallback. Separated from the launch so tests
    can assert routing decisions without running the kernel. A tuned
    plan (autotune.lookup) may override the hand-picked row block, but
    only after revalidating against the SAME divisor + VMEM gates — a
    stale or foreign artifact degrades to the default with a counted
    drop, never a Mosaic error."""
    if _platform() != "tpu" and not _interpret():
        return None, "platform is not tpu"
    n, h, wd, cin = x.shape
    kh, kw, _, cout = w.shape
    sh, sw = cfg.strides
    (plo, phi), (qlo, qhi) = cfg.padding
    oh = _out_hw(h, plo, phi, kh, sh)
    ow = _out_hw(wd, qlo, qhi, kw, sw)
    itm = jnp.dtype(x.dtype).itemsize
    bo = _divisor_block(oh, max(1, _TARGET_M // ow))
    tuned = autotune.lookup("pallas_conv", shape_class_of(x, w, cfg))
    if tuned is not None:
        tbo = int(tuned.get("bo", 0))
        if (1 <= tbo <= oh and oh % tbo == 0
                and _plan_vmem(tbo, oh, ow, cin, cout, kh, kw, sh, sw,
                               itm, cfg.has_scale, cfg.has_residual)
                <= _VMEM_BUDGET):
            bo = tbo
        else:
            autotune.plan_infeasible("pallas_conv")
    vmem = _plan_vmem(bo, oh, ow, cin, cout, kh, kw, sh, sw, itm,
                      cfg.has_scale, cfg.has_residual)
    if vmem > _VMEM_BUDGET:
        return None, ("VMEM budget: block needs ~%.1f MB > %.1f MB"
                      % (vmem / 2**20, _VMEM_BUDGET / 2**20))
    return {"bo": bo, "oh": oh, "ow": ow}, None


# ------------------------------------------------------ phase decomposition
def _phase_pack(x, kh, kw, sh, sw, plo, qlo, oh, ow):
    """Padded input -> [N, sh*sw, Hs, Ws, C] stride-phase planes.

    Plane p*sw+q holds padded rows ≡ p (mod sh), cols ≡ q (mod sw); input
    row sh*y + dy of output row y lives at row y + dy//sh of plane
    p = dy % sh — every kernel/grad access becomes a STATIC stride-1
    slice (no strided loads for Mosaic, no strided scatters in the
    backward)."""
    n, h, wd, c = x.shape
    hs = oh + (kh - 1) // sh
    ws = ow + (kw - 1) // sw
    hp, wp = sh * hs, sw * ws
    x = jnp.pad(x, ((0, 0), (plo, max(0, hp - h - plo)),
                    (qlo, max(0, wp - wd - qlo)), (0, 0)))
    x = x[:, :hp, :wp]  # rows the conv never reads need no phase slot
    x = x.reshape(n, hs, sh, ws, sw, c).transpose(0, 2, 4, 1, 3, 5)
    return x.reshape(n, sh * sw, hs, ws, c)


def _phase_unpack_add(dplanes, h, wd, plo, qlo, sh, sw):
    """[N, sh*sw, Hs, Ws, C] gradient planes -> [N, H, W, C] (inverse of
    _phase_pack; padding rows are dropped, cropped rows restored as 0)."""
    n, _, hs, ws, c = dplanes.shape
    hp, wp = sh * hs, sw * ws
    d = dplanes.reshape(n, sh, sw, hs, ws, c).transpose(0, 3, 1, 4, 2, 5)
    d = d.reshape(n, hp, wp, c)
    d = jnp.pad(d, ((0, 0), (0, max(0, plo + h - hp)),
                    (0, max(0, qlo + wd - wp)), (0, 0)))
    return d[:, plo:plo + h, qlo:qlo + wd]


# ------------------------------------------------------------ pallas forward
def _conv_kernel(*refs, kh, kw, sh, sw, bo, ow, cin, cout, cfg):
    it = iter(refs)
    x_ref = next(it)                       # [1, sh*sw, bo_in, ws, cin]
    w_ref = next(it)                       # [kh, kw, cin, cout]
    scale_ref = next(it) if cfg.has_scale else None      # [1, cout]
    bias_ref = next(it) if cfg.has_bias else None        # [1, cout]
    res_ref = next(it) if cfg.has_residual else None     # [1, bo, ow, cout]
    out_ref = next(it)                     # [1, bo, ow, cout]
    craw_ref = next(it) if cfg.has_scale else None       # f32 conv output

    x = x_ref[0]
    # f32 operands keep reference-parity numerics; bf16 runs the
    # single-pass MXU form with the f32 accumulator requested below
    # (the flash_attention precision policy)
    prec = (lax.Precision.HIGHEST if x.dtype == jnp.float32
            else lax.Precision.DEFAULT)
    acc = jnp.zeros((bo * ow, cout), jnp.float32)
    for dy in range(kh):
        p, a = dy % sh, dy // sh
        for dx in range(kw):
            q, b = dx % sw, dx // sw
            patch = x[p * sw + q, a:a + bo, b:b + ow, :]
            acc = acc + lax.dot_general(
                patch.reshape(bo * ow, cin), w_ref[dy, dx],
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32, precision=prec)
    pre = acc
    if cfg.has_scale:
        craw_ref[0] = acc.reshape(bo, ow, cout)
        pre = pre * scale_ref[0].astype(jnp.float32)
    if cfg.has_bias:
        pre = pre + bias_ref[0].astype(jnp.float32)
    if cfg.has_residual:
        pre = pre + res_ref[0].reshape(bo * ow, cout).astype(jnp.float32)
    if cfg.relu:
        pre = jnp.maximum(pre, 0.0)
    out_ref[0] = pre.reshape(bo, ow, cout).astype(out_ref.dtype)


def _forward_pallas(x, w, scale, bias, residual, cfg, geom):
    n, h, wd, cin = x.shape
    kh, kw, _, cout = w.shape
    sh, sw = cfg.strides
    (plo, _), (qlo, _) = cfg.padding
    oh, ow, bo = geom["oh"], geom["ow"], geom["bo"]
    nb = oh // bo
    bo_in = bo + (kh - 1) // sh
    ws = ow + (kw - 1) // sw
    out_dtype = jnp.promote_types(x.dtype, w.dtype)

    xp = _phase_pack(x, kh, kw, sh, sw, plo, qlo, oh, ow)
    # halo-materialize the row blocks so grid-step offsets are multiples
    # of the block shape (BlockSpec index maps address whole blocks);
    # adjacent blocks duplicate only the (kh-1)//sh halo rows
    ridx = jnp.arange(nb)[:, None] * bo + jnp.arange(bo_in)[None, :]
    xb = xp[:, :, ridx]                       # [n, P, nb, bo_in, ws, cin]
    xb = xb.transpose(0, 2, 1, 3, 4, 5).reshape(
        n * nb, sh * sw, bo_in, ws, cin)

    operands = [xb, w]
    in_specs = [
        pl.BlockSpec((1, sh * sw, bo_in, ws, cin),
                     lambda i: (i, 0, 0, 0, 0)),
        pl.BlockSpec((kh, kw, cin, cout), lambda i: (0, 0, 0, 0)),
    ]
    if cfg.has_scale:
        operands.append(scale.reshape(1, cout))
        in_specs.append(pl.BlockSpec((1, cout), lambda i: (0, 0)))
    if cfg.has_bias:
        operands.append(bias.reshape(1, cout))
        in_specs.append(pl.BlockSpec((1, cout), lambda i: (0, 0)))
    if cfg.has_residual:
        operands.append(residual.reshape(n * nb, bo, ow, cout))
        in_specs.append(pl.BlockSpec((1, bo, ow, cout),
                                     lambda i: (i, 0, 0, 0)))
    out_specs = [pl.BlockSpec((1, bo, ow, cout), lambda i: (i, 0, 0, 0))]
    out_shape = [jax.ShapeDtypeStruct((n * nb, bo, ow, cout), out_dtype)]
    if cfg.has_scale:  # raw conv output saved for d(scale) — flash's lse
        out_specs.append(pl.BlockSpec((1, bo, ow, cout),
                                      lambda i: (i, 0, 0, 0)))
        out_shape.append(
            jax.ShapeDtypeStruct((n * nb, bo, ow, cout), jnp.float32))

    kernel = functools.partial(_conv_kernel, kh=kh, kw=kw, sh=sh, sw=sw,
                               bo=bo, ow=ow, cin=cin, cout=cout, cfg=cfg)
    res = pl.pallas_call(
        kernel,
        grid=(n * nb,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=_interpret(),
    )(*operands)
    out = res[0].reshape(n, oh, ow, cout)
    craw = res[1].reshape(n, oh, ow, cout) if cfg.has_scale else None
    return out, craw


# -------------------------------------------------------------- xla fallback
def _xla_conv(x, w, cfg, pet=None):
    """The conv conv_fast's terminal branch would run (same precision
    policy), used off-TPU / over-budget. Without a scale epilogue pet is
    None, so the lever A/B compares IDENTICAL conv numerics; the affine
    form requests the f32 accumulator the kernel also keeps (conv_raw
    feeds d(scale))."""
    from ..precision_util import mxu_precision
    return lax.conv_general_dilated(
        x, w, window_strides=cfg.strides, padding=cfg.padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        precision=mxu_precision(x, w),
        preferred_element_type=pet)


def _forward_xla(x, w, scale, bias, residual, cfg):
    out_dt = jnp.promote_types(x.dtype, w.dtype)
    if cfg.has_scale:
        craw = _xla_conv(x, w, cfg, jnp.float32)
        pre = craw * scale.astype(jnp.float32)
        if cfg.has_bias:
            pre = pre + bias.astype(jnp.float32)
        if cfg.has_residual:
            pre = pre + residual.astype(jnp.float32)
        if cfg.relu:
            pre = jnp.maximum(pre, 0.0)
        return pre.astype(out_dt), craw
    # no affine: mirror conv_fast's terminal branch op for op, so
    # flipping MXTPU_PALLAS_CONV off-TPU never changes a program's math
    out = _xla_conv(x, w, cfg)
    if cfg.has_bias:
        out = out + bias
    if cfg.has_residual:
        out = out + residual
    if cfg.relu:
        out = jnp.maximum(out, 0)
    return out.astype(out_dt), None


# ------------------------------------------------------------------ backward
def _conv_grads_blockwise(x, w, dz, cfg):
    """dL/dx and dL/dw from the conv cotangent dz [N, OH, OW, C_out],
    blockwise over the batch via lax.scan (flash-attention-style bounded
    memory): per block, im2col patches give dW += patches^T @ dz_b and
    dpatches = dz_b @ W^T, scattered back through the phase planes with
    static adds (col2im). Exact — parity vs jax's own conv transpose is
    pinned in tests."""
    n, h, wd, cin = x.shape
    kh, kw, _, cout = w.shape
    sh, sw = cfg.strides
    (plo, _), (qlo, _) = cfg.padding
    oh = _out_hw(h, plo, cfg.padding[0][1], kh, sh)
    ow = _out_hw(wd, qlo, cfg.padding[1][1], kw, sw)
    k_col = kh * kw * cin
    prec = (lax.Precision.HIGHEST if x.dtype == jnp.float32
            else lax.Precision.DEFAULT)

    xp = _phase_pack(x, kh, kw, sh, sw, plo, qlo, oh, ow)
    wmat = w.reshape(k_col, cout)
    # bound the materialized patches per scan block
    want = max(1, _BWD_COLS_BUDGET // max(1, oh * ow * k_col
                                          * jnp.dtype(x.dtype).itemsize))
    bn = _divisor_block(n, want)

    taps = [(dy, dx) for dy in range(kh) for dx in range(kw)]

    def body(dw_acc, i):
        xb = lax.dynamic_slice_in_dim(xp, i * bn, bn, axis=0)
        dzb = lax.dynamic_slice_in_dim(dz, i * bn, bn, axis=0)
        cols = []
        for dy, dx in taps:
            p, a = dy % sh, dy // sh
            q, b = dx % sw, dx // sw
            cols.append(xb[:, p * sw + q, a:a + oh, b:b + ow, :])
        patches = jnp.concatenate(cols, axis=-1)      # [bn, oh, ow, K]
        m = bn * oh * ow
        pm = patches.reshape(m, k_col)
        zm = dzb.reshape(m, cout)
        dw_acc = dw_acc + lax.dot_general(
            pm, zm, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=prec)
        dpatches = lax.dot_general(
            zm, wmat, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=prec)
        dpatches = dpatches.reshape(bn, oh, ow, k_col)
        dplanes = jnp.zeros(xb.shape, jnp.float32)
        for t, (dy, dx) in enumerate(taps):
            p, a = dy % sh, dy // sh
            q, b = dx % sw, dx // sw
            dplanes = dplanes.at[:, p * sw + q, a:a + oh, b:b + ow, :].add(
                dpatches[..., t * cin:(t + 1) * cin])
        dxb = _phase_unpack_add(dplanes, h, wd, plo, qlo, sh, sw)
        return dw_acc, dxb.astype(x.dtype)

    dw, dx_blocks = lax.scan(body, jnp.zeros((k_col, cout), jnp.float32),
                             jnp.arange(n // bn))
    # scan stacks [n_blocks, bn, h, w, c]; block i IS batch [i*bn, (i+1)*bn)
    # so the flatten is a plain reshape — no axis swap
    dx = dx_blocks.reshape(x.shape)
    return dx, dw.reshape(w.shape).astype(w.dtype)


# ------------------------------------------------------------- custom vjp op
@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def _fused_conv_core(x, w, scale, bias, residual, cfg):
    out, _ = _core_fwd_impl(x, w, scale, bias, residual, cfg)
    return out


def _core_fwd_impl(x, w, scale, bias, residual, cfg):
    geom, reason = _resolve(x, w, cfg)
    if geom is None:
        _count_fallback(reason)
        out, craw = _forward_xla(x, w, scale, bias, residual, cfg)
    else:
        from ... import telemetry
        telemetry.inc("pallas_conv.pallas")
        out, craw = _forward_pallas(x, w, scale, bias, residual, cfg, geom)
    # residuals carry only what the backward reads: `out` feeds the ReLU
    # mask alone, and d_residual is just the (cast) cotangent — saving
    # either tensor unconditionally would hold an extra output-sized
    # buffer per gated conv from forward to backward
    return out, (x, w, scale, bias, out if cfg.relu else None, craw)


def _core_fwd(x, w, scale, bias, residual, cfg):
    return _core_fwd_impl(x, w, scale, bias, residual, cfg)


def _core_bwd(cfg, res, g):
    x, w, scale, bias, out, craw = res
    g32 = g.astype(jnp.float32)
    if cfg.relu:
        g32 = jnp.where(out > 0, g32, 0.0)
    d_residual = (g32.astype(cfg.res_dtype) if cfg.has_residual else None)
    d_bias = (jnp.sum(g32, axis=(0, 1, 2)).astype(bias.dtype)
              if cfg.has_bias else None)
    if cfg.has_scale:
        d_scale = jnp.sum(g32 * craw, axis=(0, 1, 2)).astype(scale.dtype)
        dz32 = g32 * scale.astype(jnp.float32)
    else:
        d_scale = None
        dz32 = g32
    # matched-operand MXU form for the two grad contractions (conv_acc's
    # reasoning: the cotangent meets the saved operands in their dtype,
    # accumulation stays f32 via preferred_element_type)
    dz = dz32.astype(jnp.promote_types(x.dtype, w.dtype))
    dx, dw = _conv_grads_blockwise(x, w, dz, cfg)
    return dx, dw, d_scale, d_bias, d_residual


_fused_conv_core.defvjp(_core_fwd, _core_bwd)


def fused_conv(x, w, strides=(1, 1), padding=((0, 0), (0, 0)), scale=None,
               bias=None, residual=None, relu=False):
    """relu(conv(x, w) * scale + bias + residual) in one fused pass.

    NHWC x [N, H, W, C_in], HWIO w [kh, kw, C_in, C_out]; ``scale``/
    ``bias`` are per-C_out vectors (a BN one-pass affine folds to exactly
    this form), ``residual`` an output-shaped tensor (the bottleneck-block
    shortcut), all optional. Differentiable in x, w, scale, bias,
    residual. Falls back to the XLA conv (+ unfused epilogue) off-TPU or
    when the shape exceeds the VMEM plan — same signature, same math."""
    cfg = _Cfg(strides=tuple(int(s) for s in strides),
               padding=tuple((int(a), int(b)) for a, b in padding),
               relu=bool(relu),
               has_scale=scale is not None,
               has_bias=bias is not None,
               has_residual=residual is not None,
               res_dtype=("" if residual is None
                          else jnp.dtype(residual.dtype).name))
    return _fused_conv_core(x, w, scale, bias, residual, cfg)


# ------------------------------------------------------- autotune descriptor
def _class_geom(sc):
    """(oh, ow, itemsize) from an autotune shape-class dict."""
    oh = _out_hw(sc["h"], sc["p0"], sc["p1"], sc["kh"], sc["sh"])
    ow = _out_hw(sc["w"], sc["q0"], sc["q1"], sc["kw"], sc["sw"])
    return oh, ow, jnp.dtype(sc["dtype"]).itemsize


# candidate GEMM-M targets the space sweeps; each realizes to the largest
# divisor row block bo <= target/ow, so the space covers "fewer, fatter
# grid steps" through "many thin ones" around the hand-picked _TARGET_M
_TUNE_TARGET_M = (256, 512, 1024, 2048, 4096, 8192, 16384)


def _tune_space(sc):
    oh, ow, _ = _class_geom(sc)
    return [{"bo": _divisor_block(oh, max(1, tm // ow))}
            for tm in _TUNE_TARGET_M]


def _tune_default(sc):
    oh, ow, _ = _class_geom(sc)
    return {"bo": _divisor_block(oh, max(1, _TARGET_M // ow))}


def _tune_feasible(plan, sc):
    oh, ow, itm = _class_geom(sc)
    bo = int(plan.get("bo", 0))
    if not (1 <= bo <= oh and oh % bo == 0):
        return False, "bo=%d is not a divisor of oh=%d" % (bo, oh)
    vmem = _plan_vmem(bo, oh, ow, sc["cin"], sc["cout"], sc["kh"],
                      sc["kw"], sc["sh"], sc["sw"], itm,
                      bool(sc["scale"]), bool(sc["res"]))
    if vmem > _VMEM_BUDGET:
        return False, ("VMEM budget: bo=%d needs ~%.1f MB > %.1f MB"
                       % (bo, vmem / 2**20, _VMEM_BUDGET / 2**20))
    return True, None


def _tune_runner(sc):
    """Real buffers + a dispatch through fused_conv's public entry (the
    timed program IS the serving program for this shape class)."""
    import numpy as np
    rng = np.random.default_rng(0)
    dt = jnp.dtype(sc["dtype"])
    x = jnp.asarray(rng.standard_normal(
        (sc["n"], sc["h"], sc["w"], sc["cin"])), dt)
    w = jnp.asarray(0.1 * rng.standard_normal(
        (sc["kh"], sc["kw"], sc["cin"], sc["cout"])), dt)
    strides = (sc["sh"], sc["sw"])
    padding = ((sc["p0"], sc["p1"]), (sc["q0"], sc["q1"]))
    oh, ow, _ = _class_geom(sc)
    args = [x, w]
    has_scale, has_res = bool(sc["scale"]), bool(sc["res"])
    if has_scale:
        args.append(jnp.asarray(
            1.0 + 0.1 * rng.standard_normal(sc["cout"]), jnp.float32))
    if has_res:
        args.append(jnp.asarray(rng.standard_normal(
            (sc["n"], oh, ow, sc["cout"])), dt))

    def fn(*a):
        it = iter(a)
        xx, ww = next(it), next(it)
        sc_v = next(it) if has_scale else None
        rs_v = next(it) if has_res else None
        return fused_conv(xx, ww, strides=strides, padding=padding,
                          scale=sc_v, residual=rs_v, relu=True)

    return fn, tuple(args)


def _tune_classes(host_tier):
    """Representative shape classes a tuning session sweeps (the bench
    conv_class families). The host tier shrinks batch/H so interpret-mode
    candidates stay inside the perf-battery budget; on a chip the bench
    shapes run as-is."""
    if host_tier:
        geoms = [(2, 64, 3, 64, 7, 2, 3),     # stem 7x7s2
                 (2, 28, 256, 64, 1, 1, 0),   # bottleneck pointwise
                 (2, 28, 64, 64, 3, 1, 1)]    # stage-2 spatial
    else:
        geoms = [(8, 224, 3, 64, 7, 2, 3),
                 (8, 56, 256, 64, 1, 1, 0),
                 (8, 56, 64, 64, 3, 1, 1)]
    return [{"n": n, "h": h, "w": h, "cin": cin, "kh": k, "kw": k,
             "cout": cout, "sh": s, "sw": s, "p0": p, "p1": p,
             "q0": p, "q1": p, "dtype": "float32", "scale": 1, "res": 0}
            for (n, h, cin, cout, k, s, p) in geoms]


autotune.register_kernel(autotune.TunableKernel(
    kernel_id="pallas_conv",
    space=_tune_space,
    default=_tune_default,
    feasible=_tune_feasible,
    runner=_tune_runner,
    classes=_tune_classes,
    interpret_env="MXTPU_PALLAS_CONV_INTERPRET",
))
