"""Measured Pallas block-shape autotuner (ROADMAP item 1).

The hand kernels in this package ship with hand-picked launch geometry:
``conv.py`` derives its output row-block ``bo`` from a fixed
``_TARGET_M`` and ``flash_attention.py`` defaults to 512/512 q/k blocks.
Those defaults were picked against one chip generation and one model
family; the per-site roofline ledger (``telemetry_report --ledger``,
arXiv:2301.13062) shows which sites are memory-bound enough for block
geometry to matter, and the TVM line of work (arXiv:1802.04799) shows
measured search over a declared parameter space reliably beats
hand-picked schedules. This module is that search engine, generic over
the kernel fleet:

* **Plan spaces** — each kernel registers a :class:`TunableKernel`
  descriptor declaring its candidate plans (block shapes, row splits),
  its hand-picked default, a ``_resolve``-style feasibility check that
  rejects VMEM-overflow plans BEFORE any compile, and a runner that
  dispatches the kernel on real buffers.
* **Measured search** — :func:`search` times every feasible candidate
  with warmup-discarded median-of-rounds dispatches (the first dispatch
  carries trace+compile and is thrown away), bounded by
  ``MXTPU_AUTOTUNE_BUDGET_S`` wall clock. The search runs on whatever
  backend is live: on a chip the real kernel is timed, on the host tier
  the kernel's interpret lever is raised so block geometry still
  executes (slower absolute numbers, same machinery — the chip/tunnel
  has been wedged since BENCH_r03 and the subsystem must not rot).
* **Persistent plan artifacts** — winning plans serialize under
  ``MXTPU_COMPILE_CACHE_DIR`` next to the compile service's executable
  blobs, keyed by (kernel id, shape class incl. dtype, device kind),
  committed tmp+rename with a self-describing JSON header. Every
  load-time mismatch — truncated/garbage blob, format/device skew, a
  forged or collided digest — degrades to the hand-picked default with
  an ``autotune.drops{reason}`` count (the PR-15 failure-matrix
  discipline): the plan cache can never crash a trace and can never
  serve another device's geometry.
* **Zero warm-start searches** — ``MXTPU_AUTOTUNE=1`` makes the kernels
  consult :func:`lookup` at trace time; the plan table is loaded from
  disk ONCE per process, so a restarted trainer or fresh replica serves
  tuned plans with zero searches. ``compile_service.warmup`` preloads
  the table before any tracing, which ships tuned plans fleet-wide
  through the existing ReplicaSet/Trainer warmup path.
* **Plan identity rides the jit cache key** — :func:`policy_token` is a
  component of ``registry.policy_key()`` (the way ``MeshPlan``
  fingerprints ride the sharding component): installing a different
  tuned plan changes every policy-keyed cache digest, so a plan flip
  can never alias an executable traced under the old geometry; sites
  that key on an explicit policy subset (the fused optimizer) never
  recompile.

Observability: ``autotune.searches`` / ``autotune.plan_hits{source}`` /
``autotune.plan_misses`` / ``autotune.drops{reason}`` counters and the
``pallas.plan{kernel}`` gauge family (fingerprint of the last plan
served per kernel; 0 = hand-picked default). The observe → tune →
persist → serve loop and the artifact format live in docs/autotune.md.
"""
from __future__ import annotations

import contextlib
import hashlib
import json
import os
import tempfile
import threading
import time
from typing import Callable, NamedTuple, Optional

__all__ = ["TunableKernel", "register_kernel", "kernels", "enabled",
           "lookup", "active_plan", "plan_id_of", "forced", "search",
           "install_plan", "save_plan", "ensure_loaded", "policy_token",
           "reset"]

FORMAT_VERSION = 1
_MAGIC = "MXTPU-AT"
_PREFIX = "plan_"
_SUFFIX = ".mxp"

_LOCK = threading.RLock()
_PLANS = {}        # (kernel_id, class token) -> {plan, plan_id, source}
_FORCED = {}       # kernel_id -> [plan, ...] (innermost last)
_STATE = {"loaded": False, "digest": None}


class TunableKernel(NamedTuple):
    """One kernel's declared tunable surface.

    ``space(sc)`` yields candidate plan dicts for a shape class,
    ``default(sc)`` the hand-picked plan (always timed first and always
    the degradation target), ``feasible(plan, sc)`` the pre-compile
    VMEM/divisibility gate returning ``(ok, reason)``, ``runner(sc)``
    a ``(fn, args)`` pair dispatching the kernel on real buffers, and
    ``classes(host_tier)`` the representative shape classes a tuning
    session sweeps when the ledger queue names the kernel's sites.
    ``interpret_env`` is the kernel's interpret lever, raised by the
    search off-TPU so candidates execute on the host tier."""
    kernel_id: str
    space: Callable
    default: Callable
    feasible: Callable
    runner: Callable
    classes: Callable
    interpret_env: Optional[str] = None


_KERNELS = {}


def register_kernel(tk: TunableKernel):
    _KERNELS[tk.kernel_id] = tk
    return tk


def kernels():
    return dict(_KERNELS)


# --------------------------------------------------------------- env levers
def enabled():
    """MXTPU_AUTOTUNE=1 serves tuned plans at trace time. Trace-time
    lever: the default mirrors the registry.policy_key entry."""
    return os.environ.get("MXTPU_AUTOTUNE", "0") == "1"


def _rounds(override=None):
    if override is not None:
        return max(1, int(override))
    # host-side search knob (timed rounds per candidate) — read only by
    # search(), never inside a trace
    return max(1, int(os.environ.get("MXTPU_AUTOTUNE_ROUNDS", "3")))  # graftlint: disable=policy-key-coverage


def _budget_s(override=None):
    if override is not None:
        return float(override)
    # host-side search knob (wall budget per search) — never traced
    return float(os.environ.get("MXTPU_AUTOTUNE_BUDGET_S", "30"))  # graftlint: disable=policy-key-coverage


# ------------------------------------------------------------- key material
def class_token(shape_class):
    """Deterministic token for a shape class: sorted ``k=v`` pairs. The
    class dict must already carry the dtype — (kernel, class, dtype,
    device) is the full artifact key."""
    return "|".join("%s=%s" % (k, shape_class[k])
                    for k in sorted(shape_class))


def device_kind():
    """Plan artifacts are geometry, not code, so they key on the chip
    KIND (platform + device_kind), not the jax/jaxlib ABI the
    executable cache must pin."""
    try:
        import jax
        d = jax.devices()[0]
        return "%s/%s" % (d.platform, getattr(d, "device_kind", "?"))
    except Exception:  # noqa: BLE001 — a dead PJRT client still keys
        return "unknown"


def _key_material(kernel_id, token, device):
    return "%s|%s|%s|fmt%d" % (kernel_id, token, device, FORMAT_VERSION)


def _digest(kernel_id, token, device):
    mat = _key_material(kernel_id, token, device)
    return hashlib.sha256(mat.encode("utf-8")).hexdigest()[:20]


def plan_path(kernel_id, shape_class, root=None):
    """Artifact path for (kernel, class, device) under the compile
    service's cache dir, or None when the disk cache is off."""
    from ... import compile_service
    root = root or compile_service.cache_dir()
    if not root:
        return None
    token = class_token(shape_class)
    return os.path.join(root, _PREFIX
                        + _digest(kernel_id, token, device_kind())
                        + _SUFFIX)


def plan_id_of(plan):
    """Stable human-readable plan identity, e.g. ``bo=16`` or
    ``block_k=256,block_q=512`` — what bench lines and artifacts
    stamp."""
    return ",".join("%s=%s" % (k, plan[k]) for k in sorted(plan))


def _plan_fingerprint(plan_id):
    """Small numeric fingerprint for the ``pallas.plan{kernel}`` gauge
    (0 is reserved for the hand-picked default)."""
    h = hashlib.sha256(plan_id.encode("utf-8")).hexdigest()[:6]
    return int(h, 16) or 1


# ------------------------------------------------------------------ serving
def _drop(reason, kernel_id, path=None):
    from ... import telemetry
    telemetry.inc("autotune.drops", tag=reason)
    return None


def _gauge(kernel_id, plan_id):
    from ... import telemetry
    telemetry.gauge("pallas.plan",
                    0 if plan_id is None else _plan_fingerprint(plan_id),
                    tag=kernel_id)


def lookup(kernel_id, shape_class):
    """The kernels' trace-time consult: the tuned plan dict for this
    (kernel, shape class, device), or None → hand-picked default.
    Forced plans (the search / parity tests) win over everything;
    otherwise the table is served only under ``MXTPU_AUTOTUNE=1``.
    Counts ``autotune.plan_hits{source}`` / ``autotune.plan_misses``
    and publishes the ``pallas.plan{kernel}`` gauge."""
    from ... import telemetry
    stack = _FORCED.get(kernel_id)
    if stack:
        plan = dict(stack[-1])
        telemetry.inc("autotune.plan_hits", tag="forced")
        return plan
    if not enabled():
        return None
    ensure_loaded()
    with _LOCK:
        rec = _PLANS.get((kernel_id, class_token(shape_class)))
    if rec is None:
        telemetry.inc("autotune.plan_misses")
        _gauge(kernel_id, None)
        return None
    telemetry.inc("autotune.plan_hits", tag=rec["source"])
    _gauge(kernel_id, rec["plan_id"])
    return dict(rec["plan"])


def plan_infeasible(kernel_id, reason="infeasible"):
    """A served plan failed the kernel's own revalidation (divisor /
    VMEM) — the kernel degrades to its default and the drop counts.
    Exposed for the kernels' consult sites."""
    return _drop(reason, kernel_id)


def active_plan(kernel_id, shape_class):
    """(plan_id, provenance) the kernel would use for this class right
    now — ``("<plan id>", "tuned")`` or ``(None, "default")``. The
    bench stamps this into every JSON line."""
    plan = lookup(kernel_id, shape_class)
    if plan is None:
        return None, "default"
    tk = _KERNELS.get(kernel_id)
    if tk is not None and plan == tk.default(shape_class):
        return plan_id_of(plan), "default"
    return plan_id_of(plan), "tuned"


@contextlib.contextmanager
def forced(kernel_id, plan):
    """Force ``plan`` for every ``lookup`` of ``kernel_id`` inside the
    context — how the search times candidates and how the parity tests
    pin every candidate the search may emit."""
    with _LOCK:
        _FORCED.setdefault(kernel_id, []).append(dict(plan))
    try:
        yield
    finally:
        with _LOCK:
            _FORCED[kernel_id].pop()
            if not _FORCED[kernel_id]:
                del _FORCED[kernel_id]


# -------------------------------------------------------------- persistence
def save_plan(kernel_id, shape_class, plan, meta=None, root=None):
    """Serialize a winning plan tmp+rename under the compile-service
    cache dir. Self-describing JSON: magic + env (format, device kind) +
    the full key material, so a forged rename or a foreign device's
    artifact is detected at load. Returns the committed path or None
    (disk cache off / IO failure — counted, never raised)."""
    path = plan_path(kernel_id, shape_class, root)
    if path is None:
        return None
    token = class_token(shape_class)
    rec = {"magic": _MAGIC,
           "env": {"format": FORMAT_VERSION, "device": device_kind()},
           "kernel": kernel_id,
           "class": token,
           "key": _key_material(kernel_id, token, device_kind()),
           "plan": dict(plan),
           "plan_id": plan_id_of(plan),
           "meta": dict(meta or {}),
           "created": time.time()}
    try:
        root_dir = os.path.dirname(path)
        os.makedirs(root_dir, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=root_dir, suffix=".tmp")
        try:
            with os.fdopen(fd, "w", encoding="utf-8") as f:
                json.dump(rec, f, sort_keys=True)
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
    except Exception:  # noqa: BLE001 — disk full / perms / races
        return _drop("io", kernel_id, path)
    return path


def _load_blob(path):
    """One artifact → the in-memory table, or a counted drop. The
    degradation matrix mirrors the executable cache's: ``corrupt``
    (unreadable/garbage/bad magic), ``version_mismatch`` (format or
    device-kind skew), ``key_mismatch`` (digest collision or forged
    rename — the stored key material disagrees with the filename)."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            rec = json.load(f)
    except Exception:  # noqa: BLE001 — truncated/garbage blob
        return _drop("corrupt", None, path)
    if not isinstance(rec, dict) or rec.get("magic") != _MAGIC:
        return _drop("corrupt", None, path)
    env = rec.get("env")
    if env != {"format": FORMAT_VERSION, "device": device_kind()}:
        return _drop("version_mismatch", rec.get("kernel"), path)
    kernel_id = rec.get("kernel")
    token = rec.get("class")
    plan = rec.get("plan")
    if not (isinstance(kernel_id, str) and isinstance(token, str)
            and isinstance(plan, dict)):
        return _drop("corrupt", kernel_id, path)
    want_key = _key_material(kernel_id, token, device_kind())
    want_name = _PREFIX + _digest(kernel_id, token, device_kind()) + _SUFFIX
    if rec.get("key") != want_key \
            or os.path.basename(path) != want_name:
        return _drop("key_mismatch", kernel_id, path)
    with _LOCK:
        _PLANS[(kernel_id, token)] = {
            "plan": dict(plan),
            "plan_id": rec.get("plan_id") or plan_id_of(plan),
            "source": "disk"}
        _STATE["digest"] = None
    return plan


def ensure_loaded():
    """Scan the cache dir ONCE per process and install every valid plan
    artifact for this device kind — the zero-warm-start-search path. A
    no-op unless ``MXTPU_AUTOTUNE=1`` (the table is never consulted
    when the lever is off, so the scan would be waste)."""
    if not enabled():
        return
    with _LOCK:
        if _STATE["loaded"]:
            return
        _STATE["loaded"] = True
    from ... import compile_service
    root = compile_service.cache_dir()
    if not root or not os.path.isdir(root):
        return
    for name in sorted(os.listdir(root)):
        if name.startswith(_PREFIX) and name.endswith(_SUFFIX):
            _load_blob(os.path.join(root, name))


def install_plan(kernel_id, shape_class, plan, source="search"):
    """Install a plan into the serving table (and invalidate the policy
    token so every policy-keyed executable recompiles under the new
    geometry — a plan flip can never alias)."""
    with _LOCK:
        _PLANS[(kernel_id, class_token(shape_class))] = {
            "plan": dict(plan), "plan_id": plan_id_of(plan),
            "source": source}
        _STATE["digest"] = None


def installed():
    """{(kernel_id, class token): plan_id} — observability/tests."""
    with _LOCK:
        return {k: v["plan_id"] for k, v in _PLANS.items()}


def reset():
    """Drop the in-memory table and the loaded/digest state (tests; a
    fresh process is the real reset)."""
    with _LOCK:
        _PLANS.clear()
        _FORCED.clear()
        _STATE["loaded"] = False
        _STATE["digest"] = None


def policy_token():
    """The plan-identity component of ``registry.policy_key()``: "0"
    when serving is off, else a digest of the installed plan set.
    Loaded once per process, so the token is stable across every trace
    of a serving run; an in-process ``install_plan`` (a live search)
    changes it, forcing exactly the recompile the new geometry needs."""
    if not enabled():
        return "0"
    ensure_loaded()
    with _LOCK:
        if _STATE["digest"] is None:
            items = sorted((k[0], k[1], v["plan_id"])
                           for k, v in _PLANS.items())
            _STATE["digest"] = ("0" if not items else hashlib.sha256(
                repr(items).encode("utf-8")).hexdigest()[:12])
        return _STATE["digest"]


# ------------------------------------------------------------------- search
def _sync(out):
    """Host-fetch sync (the PERF.md methodology — block_until_ready does
    not reliably wait through the tunnel)."""
    import jax
    import numpy as np
    leaves = [x for x in jax.tree_util.tree_leaves(out)
              if hasattr(x, "ravel")]
    if leaves:
        np.asarray(jax.device_get(leaves[0].ravel()[:1]))


@contextlib.contextmanager
def _env_patch(name, value):
    saved = os.environ.get(name)
    os.environ[name] = value
    try:
        yield
    finally:
        if saved is None:
            os.environ.pop(name, None)
        else:
            os.environ[name] = saved


def _time_plan(kernel_id, fn, plan, args, rounds):
    """Warmup-discarded median-of-rounds wall time of one candidate
    dispatch on real buffers. The candidate executables are deliberately
    EPHEMERAL measurement probes — the persisted artifact is the PLAN,
    and the serving-path executables that embed it resolve through
    compile_service.get_or_build at their own sites (JIT_ALLOWLIST:
    autotune.search). Each probe compile still reports through
    ``record_retrace`` so the xprof executable ledger covers the site
    like every other inventory entry; the wrapper's per-call overhead is
    a counter bump, identical across candidates, so the A/B stays
    like-for-like."""
    import jax

    from ... import telemetry
    with forced(kernel_id, plan):
        jitted = jax.jit(lambda *a: fn(*a))
        jitted = telemetry.record_retrace(
            "autotune.search",
            provenance=(kernel_id, plan_id_of(plan)),
            compiled=jitted) or jitted
        _sync(jitted(*args))        # trace+compile — discarded
        ts = []
        for _ in range(rounds):
            t0 = time.perf_counter()
            _sync(jitted(*args))
            ts.append(time.perf_counter() - t0)
    ts.sort()
    return ts[len(ts) // 2]


def search(kernel_id, shape_class, rounds=None, budget_s=None,
           install=True, persist=True):
    """Measured search over one kernel's plan space for one shape class.

    Candidates are feasibility-pruned BEFORE any compile (VMEM
    overflow / non-divisor blocks never reach the backend), the
    hand-picked default is always timed first (it is the baseline the
    not-worse gates compare against), and the wall budget stops the
    sweep with best-so-far. Off-TPU the kernel's interpret lever is
    raised so geometry still executes on the host tier. Returns the
    result record; when the best plan beats the default it is installed
    (and persisted with ``MXTPU_COMPILE_CACHE_DIR`` set)."""
    from ... import telemetry
    tk = _KERNELS[kernel_id]
    telemetry.inc("autotune.searches")
    rounds = _rounds(rounds)
    budget = _budget_s(budget_s)
    default = dict(tk.default(shape_class))
    default_id = plan_id_of(default)

    cands, pruned, seen = [], [], set()
    for plan in [default] + list(tk.space(shape_class)):
        pid = plan_id_of(plan)
        if pid in seen:
            continue
        seen.add(pid)
        ok, reason = tk.feasible(plan, shape_class)
        if ok:
            cands.append(dict(plan))
        else:
            pruned.append({"plan_id": pid, "reason": reason})

    fn, args = tk.runner(shape_class)
    from .flash_attention import _platform
    ctx = (_env_patch(tk.interpret_env, "1")
           if tk.interpret_env and _platform() != "tpu"
           else contextlib.nullcontext())
    timings = []
    budget_exhausted = False
    deadline = time.monotonic() + budget
    with ctx:
        for plan in cands:
            if timings and time.monotonic() > deadline:
                budget_exhausted = True
                break
            secs = _time_plan(kernel_id, fn, plan, args, rounds)
            timings.append({"plan": plan, "plan_id": plan_id_of(plan),
                            "s": secs})
    # candidate probes are throwaway jits; nothing persists past here
    default_s = timings[0]["s"]
    best = min(timings, key=lambda r: r["s"])
    improved = best["plan_id"] != default_id and best["s"] < default_s
    result = {"kernel": kernel_id,
              "class": class_token(shape_class),
              "device": device_kind(),
              "rounds": rounds,
              "candidates": len(cands),
              "pruned": pruned,
              "timed": len(timings),
              "budget_exhausted": budget_exhausted,
              "default_plan_id": default_id,
              "default_s": default_s,
              "best_plan": dict(best["plan"]),
              "best_plan_id": best["plan_id"],
              "best_s": best["s"],
              "speedup_vs_default": (default_s / best["s"]
                                     if best["s"] > 0 else None),
              "improved": improved,
              "timings": timings,
              "persisted": None}
    if improved and install:
        install_plan(kernel_id, shape_class, best["plan"])
        if persist:
            result["persisted"] = save_plan(
                kernel_id, shape_class, best["plan"],
                meta={"default_plan_id": default_id,
                      "default_s": default_s, "best_s": best["s"],
                      "rounds": rounds, "timed": len(timings),
                      "pruned": len(pruned)})
    return result
