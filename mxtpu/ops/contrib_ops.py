"""Contrib op family (ref: src/operator/contrib/*): detection/bbox ops, resize/pool
variants, transformer helper, quadratic, fft. Implemented as XLA lowerings; the
reference's hand CUDA kernels (nms, roi_align, deformable conv) become vectorized
gather/scatter HLO."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register


@register("_contrib_div_sqrt_dim", aliases=("div_sqrt_dim",))
def div_sqrt_dim(data):
    """Scale by 1/sqrt(last dim) — the attention-score helper
    (ref: src/operator/contrib/transformer.cc)."""
    return data / jnp.sqrt(jnp.asarray(data.shape[-1], dtype=data.dtype))


@register("quadratic", aliases=("_contrib_quadratic",))
def quadratic(data, a=0.0, b=0.0, c=0.0):
    """Ref: src/operator/contrib/quadratic_op.cc (the tutorial op)."""
    return a * data * data + b * data + c


@register("_contrib_arange_like")
def contrib_arange_like(data, start=0.0, step=1.0, repeat=1, axis=None):
    from .init_ops import arange_like
    return arange_like(data, start=start, step=step, repeat=repeat, axis=axis)


# ----------------------------------------------------------- resize / pooling
@register("_contrib_BilinearResize2D", aliases=("BilinearResize2D",))
def BilinearResize2D(data, height=1, width=1, scale_height=None, scale_width=None,
                     **_ig):
    """Ref: src/operator/contrib/bilinear_resize.cc."""
    n, c, h, w = data.shape
    if scale_height is not None:
        height, width = int(h * scale_height), int(w * scale_width)
    return jax.image.resize(data, (n, c, height, width), method="bilinear")


@register("_contrib_AdaptiveAvgPooling2D", aliases=("AdaptiveAvgPooling2D",))
def AdaptiveAvgPooling2D(data, output_size=None, **_ig):
    """Ref: src/operator/contrib/adaptive_avg_pooling.cc."""
    n, c, h, w = data.shape
    if output_size is None:
        oh = ow = 1
    elif isinstance(output_size, int):
        oh = ow = output_size
    else:
        oh, ow = output_size
    # decompose into resize-style mean pooling (exact when divisible)
    if h % oh == 0 and w % ow == 0:
        x = data.reshape(n, c, oh, h // oh, ow, w // ow)
        return x.mean(axis=(3, 5))
    return jax.image.resize(data, (n, c, oh, ow), method="linear")


# ------------------------------------------------------------------ boxes
@register("_contrib_box_iou", aliases=("box_iou",))
def box_iou(lhs, rhs, format="corner"):  # noqa: A002
    """Pairwise IoU (ref: src/operator/contrib/bounding_box.cc box_iou)."""
    def to_corner(b):
        if format == "center":
            x, y, w, h = jnp.split(b, 4, axis=-1)
            return jnp.concatenate([x - w / 2, y - h / 2, x + w / 2, y + h / 2], -1)
        return b

    a = to_corner(lhs)[..., :, None, :]
    b = to_corner(rhs)[..., None, :, :]
    tl = jnp.maximum(a[..., :2], b[..., :2])
    br = jnp.minimum(a[..., 2:], b[..., 2:])
    wh = jnp.maximum(br - tl, 0)
    inter = wh[..., 0] * wh[..., 1]
    area_a = (a[..., 2] - a[..., 0]) * (a[..., 3] - a[..., 1])
    area_b = (b[..., 2] - b[..., 0]) * (b[..., 3] - b[..., 1])
    return inter / jnp.maximum(area_a + area_b - inter, 1e-12)


@register("_contrib_box_nms", aliases=("box_nms",))
def box_nms(data, overlap_thresh=0.5, valid_thresh=0.0, topk=-1, coord_start=2,
            score_index=1, id_index=-1, background_id=-1, force_suppress=False,
            in_format="corner", out_format="corner"):
    """Greedy NMS via a fixed-iteration lax loop (ref: bounding_box.cc BoxNMS).
    Suppressed boxes get score -1, matching the reference's output convention."""
    def nms_one(boxes):
        scores = boxes[:, score_index]
        coords = boxes[:, coord_start:coord_start + 4]
        n = boxes.shape[0]
        order = jnp.argsort(-scores)
        coords_s = coords[order]
        valid = scores[order] > valid_thresh
        if topk > 0:
            valid = valid & (jnp.arange(n) < topk)

        tl = jnp.maximum(coords_s[:, None, :2], coords_s[None, :, :2])
        br = jnp.minimum(coords_s[:, None, 2:], coords_s[None, :, 2:])
        wh = jnp.maximum(br - tl, 0)
        inter = wh[..., 0] * wh[..., 1]
        area = (coords_s[:, 2] - coords_s[:, 0]) * (coords_s[:, 3] - coords_s[:, 1])
        iou = inter / jnp.maximum(area[:, None] + area[None, :] - inter, 1e-12)

        def body(i, keep):
            sup = (iou[i] > overlap_thresh) & (jnp.arange(n) > i) & keep[i]
            return keep & ~sup

        keep = jax.lax.fori_loop(0, n, body, valid)
        new_scores = jnp.where(keep, scores[order], -1.0)
        out = boxes[order].at[:, score_index].set(new_scores)
        return out

    if data.ndim == 2:
        return nms_one(data)
    return jax.vmap(nms_one)(data)


@register("_contrib_ROIAlign", aliases=("ROIAlign",))
def ROIAlign(data, rois, pooled_size=(7, 7), spatial_scale=1.0, sample_ratio=-1,
             position_sensitive=False):
    """ROI Align (ref: src/operator/contrib/roi_align.cc) via bilinear gather."""
    ph, pw = pooled_size if not isinstance(pooled_size, int) else (pooled_size, pooled_size)
    n, c, h, w = data.shape
    sr = 2 if sample_ratio <= 0 else sample_ratio

    def one_roi(roi):
        batch_id = roi[0].astype(jnp.int32)
        x1, y1, x2, y2 = roi[1] * spatial_scale, roi[2] * spatial_scale, \
            roi[3] * spatial_scale, roi[4] * spatial_scale
        rw = jnp.maximum(x2 - x1, 1.0)
        rh = jnp.maximum(y2 - y1, 1.0)
        bin_w, bin_h = rw / pw, rh / ph
        # sample grid (ph*sr, pw*sr)
        ys = y1 + (jnp.arange(ph * sr) + 0.5) * bin_h / sr
        xs = x1 + (jnp.arange(pw * sr) + 0.5) * bin_w / sr
        yy, xx = jnp.meshgrid(ys, xs, indexing="ij")
        img = data[batch_id]  # (c, h, w)

        y0 = jnp.clip(jnp.floor(yy), 0, h - 1)
        x0 = jnp.clip(jnp.floor(xx), 0, w - 1)
        y1i = jnp.clip(y0 + 1, 0, h - 1)
        x1i = jnp.clip(x0 + 1, 0, w - 1)
        wy = jnp.clip(yy, 0, h - 1) - y0
        wx = jnp.clip(xx, 0, w - 1) - x0
        y0, x0, y1i, x1i = y0.astype(jnp.int32), x0.astype(jnp.int32), \
            y1i.astype(jnp.int32), x1i.astype(jnp.int32)
        v = (img[:, y0, x0] * (1 - wy) * (1 - wx) + img[:, y1i, x0] * wy * (1 - wx)
             + img[:, y0, x1i] * (1 - wy) * wx + img[:, y1i, x1i] * wy * wx)
        v = v.reshape(c, ph, sr, pw, sr).mean(axis=(2, 4))
        return v

    return jax.vmap(one_roi)(rois)


@register("ROIPooling")
def ROIPooling(data, rois, pooled_size=(7, 7), spatial_scale=1.0):
    """Max ROI pooling (ref: src/operator/roi_pooling.cc), via ROIAlign-style
    sampling with max reduction."""
    ph, pw = pooled_size if not isinstance(pooled_size, int) else (pooled_size, pooled_size)
    n, c, h, w = data.shape

    def one_roi(roi):
        batch_id = roi[0].astype(jnp.int32)
        x1 = jnp.round(roi[1] * spatial_scale).astype(jnp.int32)
        y1 = jnp.round(roi[2] * spatial_scale).astype(jnp.int32)
        x2 = jnp.round(roi[3] * spatial_scale).astype(jnp.int32)
        y2 = jnp.round(roi[4] * spatial_scale).astype(jnp.int32)
        rw = jnp.maximum(x2 - x1 + 1, 1)
        rh = jnp.maximum(y2 - y1 + 1, 1)
        img = data[batch_id]
        ys = jnp.clip(y1 + (jnp.arange(ph * 2) * rh) // (ph * 2), 0, h - 1)
        xs = jnp.clip(x1 + (jnp.arange(pw * 2) * rw) // (pw * 2), 0, w - 1)
        v = img[:, ys[:, None], xs[None, :]]
        return v.reshape(c, ph, 2, pw, 2).max(axis=(2, 4))

    return jax.vmap(one_roi)(rois)


@register("_contrib_fft", aliases=("fft",))
def fft(data, compute_size=128):
    """Ref: src/operator/contrib/fft.cc (cuFFT). Real→interleaved-complex layout."""
    f = jnp.fft.fft(data, axis=-1)
    out = jnp.stack([f.real, f.imag], axis=-1)
    return out.reshape(data.shape[:-1] + (-1,)).astype(jnp.float32)


@register("_contrib_ifft", aliases=("ifft",))
def ifft(data, compute_size=128):
    c = data.reshape(data.shape[:-1] + (-1, 2))
    z = c[..., 0] + 1j * c[..., 1]
    return jnp.real(jnp.fft.ifft(z, axis=-1)).astype(jnp.float32) * z.shape[-1]


@register("_contrib_count_sketch", aliases=("count_sketch",))
def count_sketch(data, h, s, out_dim=16, processing_batch_size=32):
    """Count sketch projection (ref: src/operator/contrib/count_sketch.cc)."""
    hh = h.astype(jnp.int32).reshape(-1)
    ss = s.reshape(-1)
    proj = jnp.zeros(data.shape[:-1] + (out_dim,), data.dtype)
    vals = data * ss
    return proj.at[..., hh % out_dim].add(vals)


@register("GridGenerator")
def GridGenerator(data, transform_type="affine", target_shape=(0, 0)):
    """Ref: src/operator/grid_generator.cc."""
    h, w = target_shape
    if transform_type == "affine":
        n = data.shape[0]
        theta = data.reshape(n, 2, 3)
        ys = jnp.linspace(-1, 1, h)
        xs = jnp.linspace(-1, 1, w)
        yy, xx = jnp.meshgrid(ys, xs, indexing="ij")
        grid = jnp.stack([xx, yy, jnp.ones_like(xx)], axis=0).reshape(3, -1)
        out = jnp.matmul(theta, grid)  # (n, 2, h*w)
        return out.reshape(n, 2, h, w)
    return data  # warp type passes flow through


@register("BilinearSampler")
def BilinearSampler(data, grid, cudnn_off=None):
    """Bilinear sampling by normalized grid (ref: src/operator/bilinear_sampler.cc)."""
    n, c, h, w = data.shape
    gx = (grid[:, 0] + 1) * (w - 1) / 2
    gy = (grid[:, 1] + 1) * (h - 1) / 2

    def sample_one(img, x, y):
        x0 = jnp.clip(jnp.floor(x), 0, w - 1)
        y0 = jnp.clip(jnp.floor(y), 0, h - 1)
        x1 = jnp.clip(x0 + 1, 0, w - 1)
        y1 = jnp.clip(y0 + 1, 0, h - 1)
        wx = jnp.clip(x, 0, w - 1) - x0
        wy = jnp.clip(y, 0, h - 1) - y0
        x0i, y0i, x1i, y1i = x0.astype(jnp.int32), y0.astype(jnp.int32), \
            x1.astype(jnp.int32), y1.astype(jnp.int32)
        v = (img[:, y0i, x0i] * (1 - wy) * (1 - wx) + img[:, y1i, x0i] * wy * (1 - wx)
             + img[:, y0i, x1i] * (1 - wy) * wx + img[:, y1i, x1i] * wy * wx)
        in_bound = (x >= 0) & (x <= w - 1) & (y >= 0) & (y <= h - 1)
        return v * in_bound.astype(v.dtype)

    return jax.vmap(sample_one)(data, gx, gy)


@register("SpatialTransformer")
def SpatialTransformer(data, loc, target_shape=(0, 0), transform_type="affine",
                       sampler_type="bilinear", cudnn_off=None):
    """Ref: src/operator/spatial_transformer.cc = GridGenerator + BilinearSampler."""
    from .registry import get_op
    g = get_op("GridGenerator").fn(loc, transform_type="affine", target_shape=target_shape)
    return get_op("BilinearSampler").fn(data, g)


# ---------------------------------------------------------------- matching
@register("_contrib_bipartite_matching", aliases=("bipartite_matching",))
def bipartite_matching(data, is_ascend=False, threshold=None, topk=-1):
    """Greedy bipartite matching on a (B, N, M) or (N, M) score matrix
    (ref: src/operator/contrib/bounding_box.cc:147). Returns (x, y):
    x[b, n] = matched column of row n (-1 unmatched), y[b, m] = matched row
    of column m. Implemented as a lax.fori_loop of argmax-pick-and-mask
    steps — min(N, M) iterations of O(NM) masked argmax, XLA-friendly."""
    squeeze = data.ndim == 2
    scores = data[None] if squeeze else data
    b, n, m = scores.shape
    neg = jnp.asarray(-jnp.inf, scores.dtype)
    sc = -scores if is_ascend else scores
    thr = None if threshold is None else (
        -threshold if is_ascend else threshold)

    limit = min(n, m) if topk is None or topk <= 0 else min(topk, n, m)

    def one(s):
        def body(_, carry):
            s_, x, y = carry
            flat = jnp.argmax(s_)
            i, j = flat // m, flat % m
            best = s_[i, j]
            ok = best > (thr if thr is not None else neg)
            x = jnp.where(ok, x.at[i].set(j.astype(jnp.int32)), x)
            y = jnp.where(ok, y.at[j].set(i.astype(jnp.int32)), y)
            s_ = jnp.where(ok, s_.at[i, :].set(neg).at[:, j].set(neg), s_)
            return s_, x, y

        x0 = jnp.full((n,), -1, jnp.int32)
        y0 = jnp.full((m,), -1, jnp.int32)
        _, x, y = jax.lax.fori_loop(0, limit, body, (s, x0, y0))
        return x.astype(data.dtype), y.astype(data.dtype)

    x, y = jax.vmap(one)(sc)
    if squeeze:
        return x[0], y[0]
    return x, y


# ------------------------------------------------- position-sensitive ROI
def _roi_bilinear_grid(img, yy, xx):
    """Bilinear-sample img (c, h, w) at float grids yy/xx -> (c, *grid)."""
    c, h, w = img.shape
    y0 = jnp.clip(jnp.floor(yy), 0, h - 1)
    x0 = jnp.clip(jnp.floor(xx), 0, w - 1)
    y1 = jnp.clip(y0 + 1, 0, h - 1)
    x1 = jnp.clip(x0 + 1, 0, w - 1)
    wy = jnp.clip(yy, 0, h - 1) - y0
    wx = jnp.clip(xx, 0, w - 1) - x0
    y0i, x0i, y1i, x1i = (a.astype(jnp.int32) for a in (y0, x0, y1, x1))
    return (img[:, y0i, x0i] * (1 - wy) * (1 - wx)
            + img[:, y1i, x0i] * wy * (1 - wx)
            + img[:, y0i, x1i] * (1 - wy) * wx
            + img[:, y1i, x1i] * wy * wx)


@register("_contrib_PSROIPooling", aliases=("PSROIPooling",))
def PSROIPooling(data, rois, spatial_scale=1.0, output_dim=1, pooled_size=7,
                 group_size=0):
    """Position-sensitive ROI pooling (ref: src/operator/contrib/
    psroi_pooling.cc): bin (i, j) of output channel c averages input channel
    c*g*g + i*g + j over that bin. TPU re-design: the reference's exact
    integer-extent average is replaced by a fixed 2x2 bilinear sample grid
    per bin (the ROIAlign discretization) so shapes stay static."""
    g = int(group_size) or int(pooled_size)
    p = int(pooled_size)
    n, c, h, w = data.shape
    sr = 2

    def one_roi(roi):
        batch_id = roi[0].astype(jnp.int32)
        x1, y1, x2, y2 = (roi[1] * spatial_scale, roi[2] * spatial_scale,
                          roi[3] * spatial_scale, roi[4] * spatial_scale)
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        ys = y1 + (jnp.arange(p * sr) + 0.5) * rh / (p * sr)
        xs = x1 + (jnp.arange(p * sr) + 0.5) * rw / (p * sr)
        yy, xx = jnp.meshgrid(ys, xs, indexing="ij")
        v = _roi_bilinear_grid(data[batch_id], yy, xx)  # (c, p*sr, p*sr)
        v = v.reshape(c, p, sr, p, sr).mean(axis=(2, 4))  # (c, p, p)
        # position-sensitive channel select: out[d, i, j] = v[d*g*g + gi*g + gj, i, j]
        v = v.reshape(output_dim, g, g, p, p)
        gi = (jnp.arange(p) * g) // p
        gj = (jnp.arange(p) * g) // p
        return v[:, gi[:, None], gj[None, :], jnp.arange(p)[:, None],
                 jnp.arange(p)[None, :]]

    return jax.vmap(one_roi)(rois)


@register("_contrib_DeformablePSROIPooling",
          aliases=("DeformablePSROIPooling",))
def DeformablePSROIPooling(data, rois, trans=None, spatial_scale=1.0,
                           output_dim=1, group_size=1, pooled_size=7,
                           part_size=0, sample_per_part=2, trans_std=0.0,
                           no_trans=False):
    """Deformable position-sensitive ROI pooling (ref: src/operator/contrib/
    deformable_psroi_pooling.cc): PSROIPooling whose bins are shifted by the
    learned normalized offsets in ``trans`` (N, 2*cls, part, part)."""
    g = int(group_size)
    p = int(pooled_size)
    pt = int(part_size) or p
    n, c, h, w = data.shape
    sr = int(sample_per_part)

    def one_roi(roi, tr):
        batch_id = roi[0].astype(jnp.int32)
        x1, y1, x2, y2 = (roi[1] * spatial_scale - 0.5,
                          roi[2] * spatial_scale - 0.5,
                          roi[3] * spatial_scale + 0.5,
                          roi[4] * spatial_scale + 0.5)
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        bin_h, bin_w = rh / p, rw / p
        # per-bin offsets from trans: (2*cls, pt, pt) -> class 0 layout like
        # the reference's class-agnostic use (cls = output channels share)
        if no_trans or tr is None:
            dy = jnp.zeros((p, p))
            dx = jnp.zeros((p, p))
        else:
            pi = (jnp.arange(p) * pt) // p
            dy = tr[0][pi[:, None], pi[None, :]] * trans_std * rh
            dx = tr[1][pi[:, None], pi[None, :]] * trans_std * rw
        sub = (jnp.arange(sr) + 0.5) / sr
        # grids (p, sr, p, sr): bin (i, j), sub-sample (a, b), both axes
        # shifted by that bin's learned offset (dy, dx)[i, j]
        i_ = jnp.arange(p)[:, None, None, None]
        a_ = sub[None, :, None, None]
        j_ = jnp.arange(p)[None, None, :, None]
        b_ = sub[None, None, None, :]
        full = (p, sr, p, sr)
        yy = jnp.broadcast_to(y1 + (i_ + a_) * bin_h + dy[:, None, :, None],
                              full)
        xx = jnp.broadcast_to(x1 + (j_ + b_) * bin_w + dx[:, None, :, None],
                              full)
        v = _roi_bilinear_grid(data[batch_id],
                               yy.reshape(p * sr, p * sr),
                               xx.reshape(p * sr, p * sr))
        v = v.reshape(c, p, sr, p, sr).mean(axis=(2, 4))
        v = v.reshape(output_dim, g, g, p, p)
        gi = (jnp.arange(p) * g) // p
        return v[:, gi[:, None], gi[None, :], jnp.arange(p)[:, None],
                 jnp.arange(p)[None, :]]

    if trans is None or no_trans:
        tr_in = jnp.zeros((rois.shape[0], 2, pt, pt), data.dtype)
    else:
        # rois carry batch ids; trans is per-image — gather per roi
        ids = rois[:, 0].astype(jnp.int32)
        tr_in = trans[ids, :2]
    return jax.vmap(one_roi)(rois, tr_in)


@register("_contrib_DeformableConvolution", aliases=("DeformableConvolution",))
def DeformableConvolution(data, offset, weight, bias=None, kernel=(3, 3),
                          stride=(1, 1), dilate=(1, 1), pad=(0, 0),
                          num_filter=None, num_group=1,
                          num_deformable_group=1, no_bias=False,
                          workspace=None, layout=None):
    """Deformable convolution v1 (ref: src/operator/contrib/
    deformable_convolution.cc, deformable_im2col.h). NCHW only, like the
    reference.

    TPU re-design: instead of the reference's deformable_im2col CUDA
    kernel, each kernel tap (ky, kx) bilinear-samples the input at
    base_grid + dilation_offset + learned_offset, producing a
    (N, Hout, Wout, C*kh*kw) tensor that contracts with the flattened
    weight on the MXU — the gather feeds one big matmul, which is the
    XLA-friendly shape of im2col.

    ``offset`` is (N, 2*kh*kw*ndg, Hout, Wout), reference channel layout
    offset[:, 2*(dg*kh*kw + k) + {0: y, 1: x}]."""
    kh, kw = kernel
    sh, sw = stride
    dh, dw = dilate
    ph, pw = pad
    n, c, h, w = data.shape
    cout = weight.shape[0]
    ndg = int(num_deformable_group)
    hout = (h + 2 * ph - dh * (kh - 1) - 1) // sh + 1
    wout = (w + 2 * pw - dw * (kw - 1) - 1) // sw + 1

    base_y = jnp.arange(hout) * sh - ph   # top-left of each window
    base_x = jnp.arange(wout) * sw - pw
    off = offset.reshape(n, ndg, kh * kw, 2, hout, wout)

    def _zero_pad_bilinear(img, yy, xx):
        """Bilinear sample with ZERO padding outside the image — each of the
        four corners contributes only if it lies in-bounds, so fractional
        taps near the border fade to zero exactly like the reference's
        deformable_im2col (deformable_im2col.h im2col_bilinear), unlike the
        clip-to-edge sampling the ROI ops use."""
        y0f = jnp.floor(yy)
        x0f = jnp.floor(xx)
        wy = yy - y0f
        wx = xx - x0f
        out = 0.0
        for (cy, wyc) in ((y0f, 1 - wy), (y0f + 1, wy)):
            for (cx, wxc) in ((x0f, 1 - wx), (x0f + 1, wx)):
                ok = (cy >= 0) & (cy <= h - 1) & (cx >= 0) & (cx <= w - 1)
                ci = jnp.clip(cy, 0, h - 1).astype(jnp.int32)
                cj = jnp.clip(cx, 0, w - 1).astype(jnp.int32)
                out = out + img[:, ci, cj] * (wyc * wxc * ok)[None]
        return out

    def one_image(img, off_i):
        # img (c, h, w); off_i (ndg, kh*kw, 2, hout, wout)
        cols = []
        cpg = c // ndg  # channels per deformable group
        for k in range(kh * kw):
            ky, kx = k // kw, k % kw
            taps = []
            for dg in range(ndg):
                yy = (base_y[:, None] + ky * dh + off_i[dg, k, 0])
                xx = (base_x[None, :] + kx * dw + off_i[dg, k, 1])
                taps.append(_zero_pad_bilinear(
                    img[dg * cpg:(dg + 1) * cpg], yy, xx))
            cols.append(jnp.concatenate(taps, axis=0))  # (c, hout, wout)
        return jnp.stack(cols, axis=1)  # (c, kh*kw, hout, wout)

    cols = jax.vmap(one_image)(data, off)  # (n, c, kh*kw, hout, wout)
    cols = cols.reshape(n, c * kh * kw, hout * wout)
    wmat = weight.reshape(cout, -1)  # (cout, c/g*kh*kw) with num_group=1
    if num_group == 1:
        out = jnp.einsum("ok,nkp->nop", wmat, cols)
    else:
        cg = c // num_group
        og = cout // num_group
        cols_g = cols.reshape(n, num_group, cg * kh * kw, hout * wout)
        wg = wmat.reshape(num_group, og, cg * kh * kw)
        out = jnp.einsum("gok,ngkp->ngop", wg, cols_g) \
            .reshape(n, cout, hout * wout)
    out = out.reshape(n, cout, hout, wout)
    if bias is not None and not no_bias:
        out = out + bias.reshape(1, -1, 1, 1)
    return out


# ------------------------------------------------------------------- RPN
def _gen_anchors(base_size, ratios, scales):
    """Faster-RCNN anchor generation (ref: src/operator/contrib/
    proposal.cc GenerateAnchors): base box -> ratio enum -> scale enum."""
    import numpy as _np
    base = _np.array([0, 0, base_size - 1, base_size - 1], _np.float32)
    w = base[2] - base[0] + 1
    h = base[3] - base[1] + 1
    cx = base[0] + 0.5 * (w - 1)
    cy = base[1] + 0.5 * (h - 1)
    out = []
    for r in ratios:
        size = w * h
        ws = _np.round(_np.sqrt(size / r))
        hs = _np.round(ws * r)
        for s in scales:
            ws_s, hs_s = ws * s, hs * s
            out.append([cx - 0.5 * (ws_s - 1), cy - 0.5 * (hs_s - 1),
                        cx + 0.5 * (ws_s - 1), cy + 0.5 * (hs_s - 1)])
    return _np.asarray(out, _np.float32)  # (A, 4)


def _proposal_one(scores, deltas, im_info, anchors, feature_stride,
                  pre_n, post_n, thresh, min_size, iou_loss):
    """RPN proposals for ONE image. scores (A, H, W) fg; deltas (A*4, H, W)."""
    a, h, w = scores.shape
    sx = jnp.arange(w, dtype=jnp.float32) * feature_stride
    sy = jnp.arange(h, dtype=jnp.float32) * feature_stride
    # boxes indexed (a, y, x)
    anc = anchors[:, None, None, :]  # (A,1,1,4)
    shift = jnp.stack([sx[None, None, :].repeat(h, 1).repeat(a, 0),
                       sy[None, :, None].repeat(w, 2).repeat(a, 0)], -1)
    boxes = jnp.concatenate([anc[..., :2] + shift, anc[..., 2:] + shift], -1)
    d = deltas.reshape(a, 4, h, w).transpose(0, 2, 3, 1)  # (A,H,W,4)
    wa = boxes[..., 2] - boxes[..., 0] + 1
    ha = boxes[..., 3] - boxes[..., 1] + 1
    cxa = boxes[..., 0] + 0.5 * (wa - 1)
    cya = boxes[..., 1] + 0.5 * (ha - 1)
    if iou_loss:
        x1 = boxes[..., 0] + d[..., 0]
        y1 = boxes[..., 1] + d[..., 1]
        x2 = boxes[..., 2] + d[..., 2]
        y2 = boxes[..., 3] + d[..., 3]
    else:
        cx = d[..., 0] * wa + cxa
        cy = d[..., 1] * ha + cya
        pw = jnp.exp(jnp.clip(d[..., 2], -10, 10)) * wa
        ph = jnp.exp(jnp.clip(d[..., 3], -10, 10)) * ha
        x1, y1 = cx - 0.5 * (pw - 1), cy - 0.5 * (ph - 1)
        x2, y2 = cx + 0.5 * (pw - 1), cy + 0.5 * (ph - 1)
    imh, imw, imscale = im_info[0], im_info[1], im_info[2]
    x1 = jnp.clip(x1, 0, imw - 1)
    y1 = jnp.clip(y1, 0, imh - 1)
    x2 = jnp.clip(x2, 0, imw - 1)
    y2 = jnp.clip(y2, 0, imh - 1)
    ms = min_size * imscale
    keep_sz = ((x2 - x1 + 1) >= ms) & ((y2 - y1 + 1) >= ms)
    sc = jnp.where(keep_sz, scores, -jnp.inf).reshape(-1)
    flat = jnp.stack([x1, y1, x2, y2], -1).reshape(-1, 4)

    k = min(pre_n, sc.shape[0])
    top_sc, top_i = jax.lax.top_k(sc, k)
    top_box = flat[top_i]
    # greedy NMS over the score-ordered top-k. The IoU row for pivot i is
    # computed inside the loop: O(k) live memory instead of a k*k matrix
    # (6000^2 f32 = 144 MB/image at reference defaults, x batch under vmap)
    area = (top_box[:, 2] - top_box[:, 0] + 1) * \
        (top_box[:, 3] - top_box[:, 1] + 1)

    def body(i, keep):
        tl = jnp.maximum(top_box[i, :2], top_box[:, :2])
        br = jnp.minimum(top_box[i, 2:], top_box[:, 2:])
        whi = jnp.maximum(br - tl + 1, 0)
        inter = whi[:, 0] * whi[:, 1]
        iou_row = inter / jnp.maximum(area[i] + area - inter, 1e-12)
        sup = (iou_row > thresh) & (jnp.arange(k) > i) & keep[i]
        return keep & ~sup

    keep = jax.lax.fori_loop(0, k, body, top_sc > -jnp.inf)
    # stable-select first post_n kept boxes (score order preserved)
    rank = jnp.cumsum(keep) - 1
    sel = jnp.where(keep & (rank < post_n), rank, post_n)
    out = jnp.zeros((post_n + 1, 4), top_box.dtype) \
        .at[sel].set(top_box)[:post_n]
    out_sc = jnp.zeros((post_n + 1,), top_sc.dtype).at[sel].set(top_sc)[:post_n]
    nkept = jnp.maximum(jnp.minimum(jnp.sum(keep), post_n), 1)
    # reference pads short lists by repeating; repeat the LAST kept box so
    # the score column stays descending
    idx = jnp.minimum(jnp.arange(post_n), nkept - 1)
    return out[idx], out_sc[idx]


@register("_contrib_MultiProposal", aliases=("MultiProposal",))
def MultiProposal(cls_prob, bbox_pred, im_info, rpn_pre_nms_top_n=6000,
                  rpn_post_nms_top_n=300, threshold=0.7, rpn_min_size=16,
                  scales=(4, 8, 16, 32), ratios=(0.5, 1, 2),
                  feature_stride=16, output_score=False, iou_loss=False):
    """Batched RPN proposal generation (ref: src/operator/contrib/
    multi_proposal.cc). Returns rois (N*post, 5) [batch_idx, x1..y2]
    (+ scores (N*post, 1) when output_score)."""
    n, a2, h, w = cls_prob.shape
    a = a2 // 2
    anchors = jnp.asarray(_gen_anchors(feature_stride, ratios, scales))

    def one(scores_i, deltas_i, info_i):
        return _proposal_one(scores_i, deltas_i, info_i, anchors,
                             feature_stride, int(rpn_pre_nms_top_n),
                             int(rpn_post_nms_top_n), threshold,
                             float(rpn_min_size), iou_loss)

    boxes, scores = jax.vmap(one)(cls_prob[:, a:], bbox_pred, im_info)
    ids = jnp.repeat(jnp.arange(n, dtype=boxes.dtype),
                     int(rpn_post_nms_top_n))
    rois = jnp.concatenate([ids[:, None], boxes.reshape(-1, 4)], axis=1)
    if output_score:
        return rois, scores.reshape(-1, 1)
    return rois


@register("_contrib_Proposal", aliases=("Proposal",))
def Proposal(cls_prob, bbox_pred, im_info, **kwargs):
    """Single-image RPN proposals (ref: src/operator/contrib/proposal.cc)
    — MultiProposal restricted to batch 1, like the reference."""
    from .registry import get_op
    return get_op("_contrib_MultiProposal").fn(cls_prob, bbox_pred, im_info,
                                               **kwargs)


@register("_contrib_switch_moe", aliases=("switch_moe",), num_outputs=2)
def switch_moe(data, router, w1, b1, w2, b2, capacity_factor=1.25):
    """Top-1 switch MoE as a registered op (backs gluon.contrib.nn.SwitchMoE;
    no reference counterpart — SURVEY §2.3 lists MoE as absent upstream).
    data (..., D) is flattened to tokens; returns (out, aux_loss)."""
    from ..parallel.moe import switch_ffn
    dim = data.shape[-1]
    toks = data.reshape(-1, dim)
    out, aux = switch_ffn(toks, router, w1, b1, w2, b2,
                          capacity_factor=capacity_factor)
    return out.reshape(data.shape), aux
