"""Contrib op family (ref: src/operator/contrib/*): detection/bbox ops, resize/pool
variants, transformer helper, quadratic, fft. Implemented as XLA lowerings; the
reference's hand CUDA kernels (nms, roi_align, deformable conv) become vectorized
gather/scatter HLO."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register


@register("_contrib_div_sqrt_dim", aliases=("div_sqrt_dim",))
def div_sqrt_dim(data):
    """Scale by 1/sqrt(last dim) — the attention-score helper
    (ref: src/operator/contrib/transformer.cc)."""
    return data / jnp.sqrt(jnp.asarray(data.shape[-1], dtype=data.dtype))


@register("quadratic", aliases=("_contrib_quadratic",))
def quadratic(data, a=0.0, b=0.0, c=0.0):
    """Ref: src/operator/contrib/quadratic_op.cc (the tutorial op)."""
    return a * data * data + b * data + c


@register("_contrib_arange_like")
def contrib_arange_like(data, start=0.0, step=1.0, repeat=1, axis=None):
    from .init_ops import arange_like
    return arange_like(data, start=start, step=step, repeat=repeat, axis=axis)


# ----------------------------------------------------------- resize / pooling
@register("_contrib_BilinearResize2D", aliases=("BilinearResize2D",))
def BilinearResize2D(data, height=1, width=1, scale_height=None, scale_width=None,
                     **_ig):
    """Ref: src/operator/contrib/bilinear_resize.cc."""
    n, c, h, w = data.shape
    if scale_height is not None:
        height, width = int(h * scale_height), int(w * scale_width)
    return jax.image.resize(data, (n, c, height, width), method="bilinear")


@register("_contrib_AdaptiveAvgPooling2D", aliases=("AdaptiveAvgPooling2D",))
def AdaptiveAvgPooling2D(data, output_size=None, **_ig):
    """Ref: src/operator/contrib/adaptive_avg_pooling.cc."""
    n, c, h, w = data.shape
    if output_size is None:
        oh = ow = 1
    elif isinstance(output_size, int):
        oh = ow = output_size
    else:
        oh, ow = output_size
    # decompose into resize-style mean pooling (exact when divisible)
    if h % oh == 0 and w % ow == 0:
        x = data.reshape(n, c, oh, h // oh, ow, w // ow)
        return x.mean(axis=(3, 5))
    return jax.image.resize(data, (n, c, oh, ow), method="linear")


# ------------------------------------------------------------------ boxes
@register("_contrib_box_iou", aliases=("box_iou",))
def box_iou(lhs, rhs, format="corner"):  # noqa: A002
    """Pairwise IoU (ref: src/operator/contrib/bounding_box.cc box_iou)."""
    def to_corner(b):
        if format == "center":
            x, y, w, h = jnp.split(b, 4, axis=-1)
            return jnp.concatenate([x - w / 2, y - h / 2, x + w / 2, y + h / 2], -1)
        return b

    a = to_corner(lhs)[..., :, None, :]
    b = to_corner(rhs)[..., None, :, :]
    tl = jnp.maximum(a[..., :2], b[..., :2])
    br = jnp.minimum(a[..., 2:], b[..., 2:])
    wh = jnp.maximum(br - tl, 0)
    inter = wh[..., 0] * wh[..., 1]
    area_a = (a[..., 2] - a[..., 0]) * (a[..., 3] - a[..., 1])
    area_b = (b[..., 2] - b[..., 0]) * (b[..., 3] - b[..., 1])
    return inter / jnp.maximum(area_a + area_b - inter, 1e-12)


@register("_contrib_box_nms", aliases=("box_nms",))
def box_nms(data, overlap_thresh=0.5, valid_thresh=0.0, topk=-1, coord_start=2,
            score_index=1, id_index=-1, background_id=-1, force_suppress=False,
            in_format="corner", out_format="corner"):
    """Greedy NMS via a fixed-iteration lax loop (ref: bounding_box.cc BoxNMS).
    Suppressed boxes get score -1, matching the reference's output convention."""
    def nms_one(boxes):
        scores = boxes[:, score_index]
        coords = boxes[:, coord_start:coord_start + 4]
        n = boxes.shape[0]
        order = jnp.argsort(-scores)
        coords_s = coords[order]
        valid = scores[order] > valid_thresh
        if topk > 0:
            valid = valid & (jnp.arange(n) < topk)

        tl = jnp.maximum(coords_s[:, None, :2], coords_s[None, :, :2])
        br = jnp.minimum(coords_s[:, None, 2:], coords_s[None, :, 2:])
        wh = jnp.maximum(br - tl, 0)
        inter = wh[..., 0] * wh[..., 1]
        area = (coords_s[:, 2] - coords_s[:, 0]) * (coords_s[:, 3] - coords_s[:, 1])
        iou = inter / jnp.maximum(area[:, None] + area[None, :] - inter, 1e-12)

        def body(i, keep):
            sup = (iou[i] > overlap_thresh) & (jnp.arange(n) > i) & keep[i]
            return keep & ~sup

        keep = jax.lax.fori_loop(0, n, body, valid)
        new_scores = jnp.where(keep, scores[order], -1.0)
        out = boxes[order].at[:, score_index].set(new_scores)
        return out

    if data.ndim == 2:
        return nms_one(data)
    return jax.vmap(nms_one)(data)


@register("_contrib_ROIAlign", aliases=("ROIAlign",))
def ROIAlign(data, rois, pooled_size=(7, 7), spatial_scale=1.0, sample_ratio=-1,
             position_sensitive=False):
    """ROI Align (ref: src/operator/contrib/roi_align.cc) via bilinear gather."""
    ph, pw = pooled_size if not isinstance(pooled_size, int) else (pooled_size, pooled_size)
    n, c, h, w = data.shape
    sr = 2 if sample_ratio <= 0 else sample_ratio

    def one_roi(roi):
        batch_id = roi[0].astype(jnp.int32)
        x1, y1, x2, y2 = roi[1] * spatial_scale, roi[2] * spatial_scale, \
            roi[3] * spatial_scale, roi[4] * spatial_scale
        rw = jnp.maximum(x2 - x1, 1.0)
        rh = jnp.maximum(y2 - y1, 1.0)
        bin_w, bin_h = rw / pw, rh / ph
        # sample grid (ph*sr, pw*sr)
        ys = y1 + (jnp.arange(ph * sr) + 0.5) * bin_h / sr
        xs = x1 + (jnp.arange(pw * sr) + 0.5) * bin_w / sr
        yy, xx = jnp.meshgrid(ys, xs, indexing="ij")
        img = data[batch_id]  # (c, h, w)

        y0 = jnp.clip(jnp.floor(yy), 0, h - 1)
        x0 = jnp.clip(jnp.floor(xx), 0, w - 1)
        y1i = jnp.clip(y0 + 1, 0, h - 1)
        x1i = jnp.clip(x0 + 1, 0, w - 1)
        wy = jnp.clip(yy, 0, h - 1) - y0
        wx = jnp.clip(xx, 0, w - 1) - x0
        y0, x0, y1i, x1i = y0.astype(jnp.int32), x0.astype(jnp.int32), \
            y1i.astype(jnp.int32), x1i.astype(jnp.int32)
        v = (img[:, y0, x0] * (1 - wy) * (1 - wx) + img[:, y1i, x0] * wy * (1 - wx)
             + img[:, y0, x1i] * (1 - wy) * wx + img[:, y1i, x1i] * wy * wx)
        v = v.reshape(c, ph, sr, pw, sr).mean(axis=(2, 4))
        return v

    return jax.vmap(one_roi)(rois)


@register("ROIPooling")
def ROIPooling(data, rois, pooled_size=(7, 7), spatial_scale=1.0):
    """Max ROI pooling (ref: src/operator/roi_pooling.cc), via ROIAlign-style
    sampling with max reduction."""
    ph, pw = pooled_size if not isinstance(pooled_size, int) else (pooled_size, pooled_size)
    n, c, h, w = data.shape

    def one_roi(roi):
        batch_id = roi[0].astype(jnp.int32)
        x1 = jnp.round(roi[1] * spatial_scale).astype(jnp.int32)
        y1 = jnp.round(roi[2] * spatial_scale).astype(jnp.int32)
        x2 = jnp.round(roi[3] * spatial_scale).astype(jnp.int32)
        y2 = jnp.round(roi[4] * spatial_scale).astype(jnp.int32)
        rw = jnp.maximum(x2 - x1 + 1, 1)
        rh = jnp.maximum(y2 - y1 + 1, 1)
        img = data[batch_id]
        ys = jnp.clip(y1 + (jnp.arange(ph * 2) * rh) // (ph * 2), 0, h - 1)
        xs = jnp.clip(x1 + (jnp.arange(pw * 2) * rw) // (pw * 2), 0, w - 1)
        v = img[:, ys[:, None], xs[None, :]]
        return v.reshape(c, ph, 2, pw, 2).max(axis=(2, 4))

    return jax.vmap(one_roi)(rois)


@register("_contrib_fft", aliases=("fft",))
def fft(data, compute_size=128):
    """Ref: src/operator/contrib/fft.cc (cuFFT). Real→interleaved-complex layout."""
    f = jnp.fft.fft(data, axis=-1)
    out = jnp.stack([f.real, f.imag], axis=-1)
    return out.reshape(data.shape[:-1] + (-1,)).astype(jnp.float32)


@register("_contrib_ifft", aliases=("ifft",))
def ifft(data, compute_size=128):
    c = data.reshape(data.shape[:-1] + (-1, 2))
    z = c[..., 0] + 1j * c[..., 1]
    return jnp.real(jnp.fft.ifft(z, axis=-1)).astype(jnp.float32) * z.shape[-1]


@register("_contrib_count_sketch", aliases=("count_sketch",))
def count_sketch(data, h, s, out_dim=16, processing_batch_size=32):
    """Count sketch projection (ref: src/operator/contrib/count_sketch.cc)."""
    hh = h.astype(jnp.int32).reshape(-1)
    ss = s.reshape(-1)
    proj = jnp.zeros(data.shape[:-1] + (out_dim,), data.dtype)
    vals = data * ss
    return proj.at[..., hh % out_dim].add(vals)


@register("GridGenerator")
def GridGenerator(data, transform_type="affine", target_shape=(0, 0)):
    """Ref: src/operator/grid_generator.cc."""
    h, w = target_shape
    if transform_type == "affine":
        n = data.shape[0]
        theta = data.reshape(n, 2, 3)
        ys = jnp.linspace(-1, 1, h)
        xs = jnp.linspace(-1, 1, w)
        yy, xx = jnp.meshgrid(ys, xs, indexing="ij")
        grid = jnp.stack([xx, yy, jnp.ones_like(xx)], axis=0).reshape(3, -1)
        out = jnp.matmul(theta, grid)  # (n, 2, h*w)
        return out.reshape(n, 2, h, w)
    return data  # warp type passes flow through


@register("BilinearSampler")
def BilinearSampler(data, grid, cudnn_off=None):
    """Bilinear sampling by normalized grid (ref: src/operator/bilinear_sampler.cc)."""
    n, c, h, w = data.shape
    gx = (grid[:, 0] + 1) * (w - 1) / 2
    gy = (grid[:, 1] + 1) * (h - 1) / 2

    def sample_one(img, x, y):
        x0 = jnp.clip(jnp.floor(x), 0, w - 1)
        y0 = jnp.clip(jnp.floor(y), 0, h - 1)
        x1 = jnp.clip(x0 + 1, 0, w - 1)
        y1 = jnp.clip(y0 + 1, 0, h - 1)
        wx = jnp.clip(x, 0, w - 1) - x0
        wy = jnp.clip(y, 0, h - 1) - y0
        x0i, y0i, x1i, y1i = x0.astype(jnp.int32), y0.astype(jnp.int32), \
            x1.astype(jnp.int32), y1.astype(jnp.int32)
        v = (img[:, y0i, x0i] * (1 - wy) * (1 - wx) + img[:, y1i, x0i] * wy * (1 - wx)
             + img[:, y0i, x1i] * (1 - wy) * wx + img[:, y1i, x1i] * wy * wx)
        in_bound = (x >= 0) & (x <= w - 1) & (y >= 0) & (y <= h - 1)
        return v * in_bound.astype(v.dtype)

    return jax.vmap(sample_one)(data, gx, gy)


@register("SpatialTransformer")
def SpatialTransformer(data, loc, target_shape=(0, 0), transform_type="affine",
                       sampler_type="bilinear", cudnn_off=None):
    """Ref: src/operator/spatial_transformer.cc = GridGenerator + BilinearSampler."""
    from .registry import get_op
    g = get_op("GridGenerator").fn(loc, transform_type="affine", target_shape=target_shape)
    return get_op("BilinearSampler").fn(data, g)
