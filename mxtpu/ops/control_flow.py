"""Functional control-flow ops: foreach / while_loop / cond.

Reference: src/operator/control_flow.cc:476-532 — subgraphs executed as CachedOps
with state threading. TPU-native: these map *directly* onto XLA's structured control
flow (lax.scan / lax.while_loop / lax.cond), which is the whole point of functional
control flow on a compiler backend — the reference had to interpret the subgraph per
iteration; XLA compiles the body once.

The Python surface mirrors mxnet.ndarray.contrib.foreach/while_loop/cond: body
functions take and return NDArrays.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .. import autograd
from ..ndarray.ndarray import NDArray, _apply
from .registry import register


def _wrap_tree(tree):
    return jax.tree_util.tree_map(
        lambda d: NDArray(d), tree, is_leaf=lambda x: isinstance(x, jax.Array))


def _unwrap_tree(tree):
    return jax.tree_util.tree_map(
        lambda x: x._data if isinstance(x, NDArray) else jnp.asarray(x), tree,
        is_leaf=lambda x: isinstance(x, NDArray) or not isinstance(x, (list, tuple, dict)))


@register("foreach", aliases=("_foreach",), wrap=False)
def foreach(body, data, init_states):
    """Scan `body(x_t, states) -> (out_t, new_states)` over axis 0 of data
    (ref: control_flow.cc `_foreach`). Lowered to one lax.scan."""
    single_data = isinstance(data, NDArray)
    data_t = data._data if single_data else [d._data for d in data]
    single_state = isinstance(init_states, NDArray)
    states_t = init_states._data if single_state else [s._data for s in init_states]

    def scan_body(carry, x):
        x_nd = NDArray(x) if single_data else [NDArray(v) for v in x]
        c_nd = NDArray(carry) if single_state else [NDArray(v) for v in carry]
        with autograd.pause():
            out, new_states = body(x_nd, c_nd)
        out_t = out._data if isinstance(out, NDArray) else [o._data for o in out]
        ns_t = new_states._data if isinstance(new_states, NDArray) \
            else [s._data for s in new_states]
        return ns_t, out_t

    def fn(*flat_in):
        k = 1 if single_data else len(data_t)
        d = flat_in[0] if single_data else list(flat_in[:k])
        s = flat_in[k] if single_state else list(flat_in[k:])
        final, outs = lax.scan(scan_body, s, d)
        flat_outs = [outs] if not isinstance(outs, (list, tuple)) else list(outs)
        flat_final = [final] if not isinstance(final, (list, tuple)) else list(final)
        return tuple(flat_outs + flat_final)

    inputs = ([data] if single_data else list(data)) + \
        ([init_states] if single_state else list(init_states))
    results = _apply(fn, tuple(inputs), name="foreach")
    # probe structure with one eager step to split outputs vs states
    n_states = 1 if single_state else len(states_t)
    n_outs = len(results) - n_states
    outs = results[0] if n_outs == 1 else results[:n_outs]
    finals = results[n_outs] if n_states == 1 else results[n_outs:]
    return outs, finals


@register("while_loop", aliases=("_while_loop",), wrap=False)
def while_loop(cond, func, loop_vars, max_iterations=None):
    """Ref: control_flow.cc `_while_loop`. Stacked per-step outputs are not
    supported in the XLA lowering (dynamic trip count); state threading is.
    Returns ([], final_loop_vars) to match the mxnet.ndarray.contrib signature."""
    single = isinstance(loop_vars, NDArray)
    vars_list = [loop_vars] if single else list(loop_vars)

    def fn(*flat):
        def c(v):
            nd = [NDArray(x) for x in v]
            with autograd.pause():
                r = cond(*nd)
            r = r._data if isinstance(r, NDArray) else r
            return jnp.reshape(r.astype(jnp.bool_), ())

        def b(v):
            nd = [NDArray(x) for x in v]
            with autograd.pause():
                out = func(*nd)
            if isinstance(out, NDArray):
                out = [out]
            return tuple(o._data if isinstance(o, NDArray) else o for o in out)

        return lax.while_loop(c, b, tuple(flat))

    res = _apply(fn, tuple(vars_list), name="while_loop")
    return [], (res[0] if single else res)


@register("cond", aliases=("_cond",), wrap=False)
def cond(pred, then_func, else_func, inputs=None):
    """Ref: control_flow.cc `_cond`. Both branches are traced and compiled;
    XLA executes one (lax.cond)."""
    if inputs is None:
        inputs = []
    if isinstance(inputs, NDArray):
        inputs = [inputs]
    pred_nd = pred if isinstance(pred, NDArray) else NDArray(jnp.asarray(pred))

    def fn(p, *flat):
        def t(v):
            with autograd.pause():
                out = then_func(*[NDArray(x) for x in v])
            out_l = out if isinstance(out, (list, tuple)) else [out]
            return tuple(o._data for o in out_l)

        def e(v):
            with autograd.pause():
                out = else_func(*[NDArray(x) for x in v])
            out_l = out if isinstance(out, (list, tuple)) else [out]
            return tuple(o._data for o in out_l)

        return lax.cond(jnp.reshape(p.astype(jnp.bool_), ()), t, e, flat)

    res = _apply(fn, tuple([pred_nd] + list(inputs)), name="cond")
    return res if isinstance(res, list) and len(res) > 1 else \
        (res[0] if isinstance(res, list) else res)
