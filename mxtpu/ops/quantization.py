"""INT8 quantization ops (ref: src/operator/quantization/*).

The reference pairs int8 kernels (cuDNN/MKL-DNN) with a graph pass that
inserts quantize/dequantize/requantize nodes and a python calibration driver
(python/mxnet/contrib/quantization.py). TPU-native: the int8 compute is one
``lax.dot_general`` / ``conv_general_dilated`` with
``preferred_element_type=int32`` — XLA lowers that to the MXU's native int8
path (2x the bf16 throughput on v5e) — and scales stay ordinary traced
scalars so calibrated models still compile into single fused programs.

Semantics follow the reference's signed-symmetric path
(quantize-inl.h:75-78): real range ``r = max(|min|, |max|)`` maps to
quantized range 127, ``q = sign(x) * min(|x| * 127/r + 0.5, 127)``.
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from .registry import register

_QMAX = 127.0


def _f32(x):
    # NOT jnp.float32(x): that is numpy's scalar type, whose __call__
    # concretizes — a traced range (the serving int8 path passes scales
    # as jit arguments so a param reload never recompiles) would raise
    # ConcretizationTypeError. asarray casts tracers and scalars alike.
    return jnp.asarray(x, jnp.float32)


def _real_range(min_range, max_range):
    return jnp.maximum(jnp.abs(min_range), jnp.abs(max_range))


@register("_contrib_quantize", aliases=("quantize",))
def quantize(data, min_range, max_range, out_type="int8"):
    """f32 -> int8 + (min, max) carried through (ref: quantize.cc).
    Returns [quantized, min_range, max_range] like the reference's 3-output
    convention so downstream quantized ops see the calibration range."""
    r = _real_range(_f32(min_range), _f32(max_range))
    scale = _QMAX / r
    x = jnp.asarray(data, jnp.float32)
    q = jnp.sign(x) * jnp.minimum(jnp.abs(x) * scale + 0.5, _QMAX)
    return [lax.convert_element_type(q, jnp.int8),
            -r.astype(jnp.float32), r.astype(jnp.float32)]


@register("_contrib_dequantize", aliases=("dequantize",))
def dequantize(data, min_range, max_range, out_type="float32"):
    """int8 -> f32 (ref: dequantize.cc)."""
    r = _real_range(_f32(min_range), _f32(max_range))
    return jnp.asarray(data, jnp.float32) * (r / _QMAX)


@register("_contrib_requantize", aliases=("requantize",))
def requantize(data, min_range, max_range, min_calib_range=None,
               max_calib_range=None):
    """int32 (accumulator) -> int8 with a narrower calibrated range
    (ref: requantize.cc). min/max_range describe the int32's real range."""
    r32 = _real_range(_f32(min_range), _f32(max_range))
    real = jnp.asarray(data, jnp.float32) * (r32 / (2.0 ** 31 - 1))
    if min_calib_range is not None and max_calib_range is not None:
        r8 = _real_range(_f32(min_calib_range),
                         _f32(max_calib_range))
    else:
        r8 = r32
    q = jnp.sign(real) * jnp.minimum(jnp.abs(real) * (_QMAX / r8) + 0.5,
                                     _QMAX)
    return [lax.convert_element_type(q, jnp.int8),
            -r8.astype(jnp.float32), r8.astype(jnp.float32)]


@register("_contrib_quantized_fully_connected",
          aliases=("quantized_fully_connected",))
def quantized_fully_connected(data, weight, bias=None, min_data=None,
                              max_data=None, min_weight=None, max_weight=None,
                              min_bias=None, max_bias=None, num_hidden=None,
                              no_bias=False, flatten=True):
    """int8 x int8 -> f32 FC (ref: quantized_fully_connected.cc).

    The int8 contraction accumulates in int32 on the MXU
    (preferred_element_type), then one dequant scale maps back to real
    units; the f32 bias adds after dequant (the reference quantizes the
    bias too — shifting it into the int32 domain costs precision for no TPU
    win, so bias stays f32 here).
    """
    x = jnp.asarray(data, jnp.int8)
    if flatten and x.ndim > 2:
        x = jnp.reshape(x, (x.shape[0], -1))
    acc = lax.dot_general(x, jnp.asarray(weight, jnp.int8),
                          (((x.ndim - 1,), (1,)), ((), ())),
                          preferred_element_type=jnp.int32)
    sx = _real_range(_f32(min_data), _f32(max_data)) / _QMAX
    sw = _real_range(_f32(min_weight), _f32(max_weight)) / _QMAX
    out = acc.astype(jnp.float32) * (sx * sw)
    if bias is not None and not no_bias:
        out = out + jnp.asarray(bias, jnp.float32)
    return out


@register("_contrib_quantized_conv", aliases=("quantized_conv",))
def quantized_conv(data, weight, bias=None, min_data=None, max_data=None,
                   min_weight=None, max_weight=None, min_bias=None,
                   max_bias=None, kernel=None, stride=None, dilate=None,
                   pad=None, num_filter=None, num_group=1, no_bias=False,
                   layout=None):
    """int8 conv with int32 accumulation (ref: quantized_conv.cc)."""
    from .nn import _conv_dims, _pair
    ndim = data.ndim - 2
    stride = _pair(stride, ndim)
    dilate = _pair(dilate, ndim)
    pad = _pair(pad, ndim) if pad is not None else (0,) * ndim
    dims = _conv_dims(ndim, layout)
    channels_last = dims[0][-1] == "C"
    acc = lax.conv_general_dilated(
        jnp.asarray(data, jnp.int8), jnp.asarray(weight, jnp.int8),
        window_strides=stride, padding=[(p, p) for p in pad],
        rhs_dilation=dilate, dimension_numbers=dims,
        feature_group_count=num_group,
        preferred_element_type=jnp.int32)
    sx = _real_range(_f32(min_data), _f32(max_data)) / _QMAX
    sw = _real_range(_f32(min_weight), _f32(max_weight)) / _QMAX
    out = acc.astype(jnp.float32) * (sx * sw)
    if bias is not None and not no_bias:
        b = jnp.asarray(bias, jnp.float32)
        out = out + (b if channels_last
                     else jnp.reshape(b, (1, -1) + (1,) * ndim))
    return out


@register("_contrib_quantized_flatten", aliases=("quantized_flatten",))
def quantized_flatten(data, min_data, max_data):
    """Flatten an int8 tensor, ranges unchanged (ref: src/operator/
    quantization/quantized_flatten.cc)."""
    return (jnp.reshape(data, (data.shape[0], -1)), min_data, max_data)


@register("_contrib_quantized_pooling", aliases=("quantized_pooling",))
def quantized_pooling(data, min_data, max_data, kernel=None, pool_type="max",
                      global_pool=False, stride=None, pad=None,
                      pooling_convention="valid", layout=None):
    """Pooling on int8 data, ranges unchanged (ref: src/operator/
    quantization/quantized_pooling.cc). Max pool is exact in int8; avg
    accumulates in int32 then rounds back, like the reference's
    requantize-free path."""
    from .registry import get_op
    pool = get_op("Pooling").fn  # unwrapped: jnp in, jnp out
    if pool_type == "max":
        # reduce_window needs a matching-dtype init; int32 round-trip is
        # exact for int8 max
        out = pool(data.astype(jnp.int32), kernel=kernel, pool_type="max",
                   global_pool=global_pool, stride=stride, pad=pad,
                   pooling_convention=pooling_convention,
                   layout=layout).astype(data.dtype)
    else:
        acc = pool(data.astype(jnp.float32), kernel=kernel,
                   pool_type=pool_type, global_pool=global_pool,
                   stride=stride, pad=pad,
                   pooling_convention=pooling_convention, layout=layout)
        out = jnp.clip(jnp.round(acc), -128, 127).astype(data.dtype)
    return (out, min_data, max_data)
