"""Optimizer-update ops.

Reference: src/operator/optimizer_op.cc — update rules are *ops* so they run inside
the engine next to compute. Here they are jnp functions the Optimizer/Trainer jits
(mxtpu/optimizer) — same motivation (no host round-trip between grad and update);
XLA fuses the whole update into one kernel. Multi-precision (fp16/bf16 weights with
f32 master copy) follows the reference's mp_sgd_update pattern.

All update fns return the *new* values (functional) rather than mutating; the
NDArray-level wrappers in mx.nd mutate `weight` in place for API parity.
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import register


def _rescale_clip(grad, rescale_grad, clip_gradient, wd=None, weight=None):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    if wd is None or weight is None:
        return g
    if isinstance(wd, (int, float)) and wd == 0.0:
        # eager callers pass a Python float: keep skipping the add like the
        # pre-fused code (0*inf would turn a diverged weight into nan)
        return g
    # traced wd (fused step, optimizer_fused.py): no boolean short-circuit
    # on a Tracer; wd=0 is then a numerical no-op for finite weights
    return g + wd * weight


def sgd_update_fn(weight, grad, lr, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                  lazy_update=False):
    g = _rescale_clip(grad, rescale_grad, clip_gradient, wd, weight)
    return weight - lr * g


def sgd_mom_update_fn(weight, grad, mom, lr, momentum=0.0, wd=0.0, rescale_grad=1.0,
                      clip_gradient=-1.0, lazy_update=False):
    g = _rescale_clip(grad, rescale_grad, clip_gradient, wd, weight)
    mom_new = momentum * mom - lr * g
    return weight + mom_new, mom_new


def nag_mom_update_fn(weight, grad, mom, lr, momentum=0.0, wd=0.0, rescale_grad=1.0,
                      clip_gradient=-1.0):
    g = _rescale_clip(grad, rescale_grad, clip_gradient, wd, weight)
    mom_new = momentum * mom + g
    return weight - lr * (g + momentum * mom_new), mom_new


def adam_update_fn(weight, grad, mean, var, lr, beta1=0.9, beta2=0.999, epsilon=1e-8,
                   wd=0.0, rescale_grad=1.0, clip_gradient=-1.0, lazy_update=False):
    g = _rescale_clip(grad, rescale_grad, clip_gradient, wd, weight)
    mean_new = beta1 * mean + (1 - beta1) * g
    var_new = beta2 * var + (1 - beta2) * jnp.square(g)
    return weight - lr * mean_new / (jnp.sqrt(var_new) + epsilon), mean_new, var_new


def rmsprop_update_fn(weight, grad, n, lr, gamma1=0.95, epsilon=1e-8, wd=0.0,
                      rescale_grad=1.0, clip_gradient=-1.0, clip_weights=-1.0):
    g = _rescale_clip(grad, rescale_grad, clip_gradient, wd, weight)
    n_new = (1 - gamma1) * jnp.square(g) + gamma1 * n
    w = weight - lr * g / jnp.sqrt(n_new + epsilon)
    if clip_weights and clip_weights > 0:
        w = jnp.clip(w, -clip_weights, clip_weights)
    return w, n_new


def rmspropalex_update_fn(weight, grad, n, g_avg, delta, lr, gamma1=0.95, gamma2=0.9,
                          epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                          clip_weights=-1.0):
    g = _rescale_clip(grad, rescale_grad, clip_gradient, wd, weight)
    n_new = (1 - gamma1) * jnp.square(g) + gamma1 * n
    g_avg_new = (1 - gamma1) * g + gamma1 * g_avg
    delta_new = gamma2 * delta - lr * g / jnp.sqrt(n_new - jnp.square(g_avg_new) + epsilon)
    w = weight + delta_new
    if clip_weights and clip_weights > 0:
        w = jnp.clip(w, -clip_weights, clip_weights)
    return w, n_new, g_avg_new, delta_new


def ftrl_update_fn(weight, grad, z, n, lr, lamda1=0.01, beta=1.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0):
    g = _rescale_clip(grad, rescale_grad, clip_gradient)
    n_new = n + jnp.square(g)
    sigma = (jnp.sqrt(n_new) - jnp.sqrt(n)) / lr
    z_new = z + g - sigma * weight
    w = jnp.where(
        jnp.abs(z_new) > lamda1,
        -(z_new - jnp.sign(z_new) * lamda1) / ((beta + jnp.sqrt(n_new)) / lr + wd),
        0.0,
    )
    return w.astype(weight.dtype), z_new, n_new


def adagrad_update_fn(weight, grad, history, lr, epsilon=1e-7, wd=0.0,
                      rescale_grad=1.0, clip_gradient=-1.0):
    g = _rescale_clip(grad, rescale_grad, clip_gradient)
    hist_new = history + jnp.square(g)
    w = weight - lr * (g / jnp.sqrt(hist_new + epsilon) + wd * weight)
    return w, hist_new


def signsgd_update_fn(weight, grad, lr, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    g = _rescale_clip(grad, rescale_grad, clip_gradient)
    return weight - lr * (jnp.sign(g) + wd * weight)


def signum_update_fn(weight, grad, mom, lr, momentum=0.9, wd=0.0, rescale_grad=1.0,
                     clip_gradient=-1.0, wd_lh=0.0):
    g = _rescale_clip(grad, rescale_grad, clip_gradient, wd, weight)
    mom_new = momentum * mom - (1 - momentum) * g
    w = (1 - lr * wd_lh) * weight + lr * jnp.sign(mom_new)
    return w, mom_new


def ftml_update_fn(weight, grad, d, v, z, lr, t, beta1=0.6, beta2=0.999, epsilon=1e-8,
                   wd=0.0, rescale_grad=1.0, clip_grad=-1.0):
    g = _rescale_clip(grad, rescale_grad, clip_grad, wd, weight)
    v_new = beta2 * v + (1 - beta2) * jnp.square(g)
    d_new = (1 - beta1 ** t) / lr * (jnp.sqrt(v_new / (1 - beta2 ** t)) + epsilon)
    sigma = d_new - beta1 * d
    z_new = beta1 * z + (1 - beta1) * g - sigma * weight
    return -z_new / d_new, d_new, v_new, z_new


def _mutating(fn, n_state):
    """Make the mx.nd-style mutating wrapper: weight (and states) updated in place."""
    def wrapper(weight, grad, *states_and_args, out=None, **kwargs):
        states = list(states_and_args[:n_state])
        args = states_and_args[n_state:]
        res = fn(weight._data, grad._data, *[s._data for s in states], *args, **kwargs)
        if n_state == 0:
            weight._set_data(res)
        else:
            weight._set_data(res[0])
            for s, new in zip(states, res[1:]):
                s._set_data(new)
        return weight
    return wrapper


sgd_update = register("sgd_update", wrap=False)(_mutating(sgd_update_fn, 0))
sgd_mom_update = register("sgd_mom_update", wrap=False)(_mutating(sgd_mom_update_fn, 1))
nag_mom_update = register("nag_mom_update", wrap=False)(_mutating(nag_mom_update_fn, 1))
adam_update = register("adam_update", wrap=False)(_mutating(adam_update_fn, 2))
rmsprop_update = register("rmsprop_update", wrap=False)(_mutating(rmsprop_update_fn, 1))
rmspropalex_update = register("rmspropalex_update", wrap=False)(_mutating(rmspropalex_update_fn, 3))
ftrl_update = register("ftrl_update", wrap=False)(_mutating(ftrl_update_fn, 2))
adagrad_update = register("adagrad_update", wrap=False)(_mutating(adagrad_update_fn, 1))
signsgd_update = register("signsgd_update", wrap=False)(_mutating(signsgd_update_fn, 0))
signum_update = register("signum_update", wrap=False)(_mutating(signum_update_fn, 1))
