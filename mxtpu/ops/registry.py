"""Operator registry: the TPU-native analog of the NNVM op registry.

Reference contract: every op registers name + FInferShape/FInferType/FCompute/FGradient
attrs via ``NNVM_REGISTER_OP`` (include/mxnet/op_attr_types.h:198-301; canonical example
src/operator/nn/fully_connected.cc:239-328).

TPU-native re-design: an op is a *pure jax-traceable function* — shape/dtype inference
comes from jax's abstract evaluation (``jax.eval_shape``), the gradient from ``jax.vjp``,
and the kernel from XLA lowering (or a Pallas kernel for hot ops). So a registration
here is just ``(name, fn, aliases)``; the registry exists to

* generate the ``mx.nd.*`` imperative namespace (ref: per-op Python codegen at import,
  python/mxnet/ndarray/register.py:143-157),
* give :mod:`mxtpu.symbol` a name → fn table for deferred graph execution,
* attach NDArray methods (``x.sum()`` etc) the way the reference's frontend codegen does.
"""
from __future__ import annotations

import functools
import inspect
from typing import Callable, Dict, List, Optional

from ..ndarray.ndarray import NDArray, _apply

__all__ = ["Op", "register", "get_op", "list_ops", "invoke", "REGISTRY",
           "register_param_shapes", "get_param_shape_rule", "describe"]


class Op:
    """A registered operator: ``fn`` works on jax arrays / pytrees; wrapper works on
    NDArrays with tape recording."""

    __slots__ = ("name", "fn", "wrapper", "aliases", "as_method", "doc",
                 "num_outputs")

    def __init__(self, name: str, fn: Callable, wrapper: Callable,
                 aliases=(), as_method: bool = False, num_outputs: int = 1):
        self.name = name
        self.fn = fn
        self.wrapper = wrapper
        self.aliases = tuple(aliases)
        self.as_method = as_method
        self.doc = fn.__doc__
        self.num_outputs = num_outputs  # STATIC count (1 = single/unknown;
        # data-dependent counts are fixed up at execution)


REGISTRY: Dict[str, Op] = {}


def register(name: Optional[str] = None, aliases=(), as_method: bool = False,
             wrap: bool = True, num_outputs: int = 1):
    """Register a jnp-level op and return its NDArray-level function.

    The returned wrapper accepts NDArrays (and scalars/attrs), snapshots payloads,
    evaluates, wraps outputs, and tapes the call when autograd is recording — i.e. it
    performs the whole MXImperativeInvokeEx → Imperative::Invoke path
    (src/c_api/c_api_ndarray.cc:81, src/imperative/imperative.cc:87) in one function.
    """

    def deco(fn: Callable):
        op_name = name or fn.__name__

        if wrap:
            @functools.wraps(fn)
            def wrapper(*args, **kwargs):
                out = kwargs.pop("out", None)
                res = _apply(fn, args, kwargs, name=op_name)
                if out is not None:
                    if isinstance(res, list):
                        for o, r in zip(out if isinstance(out, (list, tuple)) else [out], res):
                            o._set_data(r._data)
                        return out
                    out._set_data(res._data)
                    return out
                return res
        else:
            wrapper = fn

        op = Op(op_name, fn, wrapper, aliases=aliases, as_method=as_method,
                num_outputs=num_outputs)
        REGISTRY[op_name] = op
        for al in aliases:
            REGISTRY[al] = op
        return wrapper

    return deco


def _autotune_plans_entry():
    """The tuned-plan-identity component of policy_key: a digest of the
    installed autotune plan set (pallas/autotune.policy_token). "0"
    whenever serving is off or no plans are installed, so the lever
    being absent changes nothing; a plan flip changes the digest, so a
    tuned-plan change can never alias an executable traced under the
    old block geometry (the MeshPlan discipline)."""
    try:
        from .pallas import autotune
        return autotune.policy_token()
    except Exception:  # noqa: BLE001 — policy_key must never raise
        return "0"


def policy_key():
    """Trace-time env policies that get BAKED INTO compiled executables
    (f32-accumulate convs, one-pass BN stats). Every jit cache keyed on
    shapes/modes must include this tuple, or flipping a policy flag
    mid-process silently reuses executables traced under the old policy
    (an A/B measurement would then compare a lever with itself)."""
    import os
    return (os.environ.get("MXTPU_CONV_ACC", "0"),
            # defaults must MIRROR their read sites (ops/nn.py:_bn_onepass,
            # pallas/flash_attention.py:_resolve_blocks) — a mismatch would
            # alias unset and the non-default value onto one cache key
            os.environ.get("MXTPU_BN_ONEPASS", "1"),
            os.environ.get("MXTPU_RING_FLASH", "0"),
            os.environ.get("MXTPU_FLASH_PAD_D", "1"),
            os.environ.get("MXTPU_CONV_IM2COL", "0"),
            os.environ.get("MXTPU_RNN_HOIST", "1"),
            # conv_acc.py:_pallas_enabled / pallas/conv.py:_interpret
            os.environ.get("MXTPU_PALLAS_CONV", "0"),
            os.environ.get("MXTPU_PALLAS_CONV_INTERPRET", "0"),
            # contrib/s2d_stem.py:stem_mode (policy-mode _StemFn)
            os.environ.get("MXTPU_S2D_STEM", "0"),
            # resilience.guard_enabled: the in-jit numerics sentinel — the
            # skip-step `where` select is baked into the fused-update
            # executable, so a guard flip must recompile (exactly once);
            # the step_ok FLAG and loss-scale VALUE are traced and never do
            os.environ.get("MXTPU_NUMERICS_GUARD", "0"),
            # resilience.divergence_every: the divergence-sentinel
            # fingerprint (f32 sum + i32 bitcast-fold of post-update
            # params+state) is compiled into the SAME fused-update
            # executable when non-zero, so an on/off flip recompiles (at
            # most once per cached executable). Only the ON BIT is
            # trace-time — the cadence VALUE is a host compare schedule,
            # so it is normalized here: retuning 8 -> 16 must not
            # invalidate every policy_key-keyed forward/serving
            # executable that never contained the fingerprint
            "0" if os.environ.get("MXTPU_DIVERGENCE_EVERY", "0")
            in ("", "0") else "1",
            # pallas/autotune.enabled / flash_attention._interpret —
            # tuned-plan serving and the flash interpret path change the
            # traced program, so both ride the key
            os.environ.get("MXTPU_AUTOTUNE", "0"),
            os.environ.get("MXTPU_FLASH_INTERPRET", "0"),
            _autotune_plans_entry())


# canonical op name -> fn(attrs) -> int: STATIC output count for ops whose
# count depends on attrs (the reference's FNumOutputs — e.g. RNN emits
# final states only when state_outputs). Consulted by the symbol composer
# so sym[i] works before execution.
NUM_OUTPUT_RULES: Dict[str, Callable] = {}


def register_num_outputs(name: str):
    def deco(fn: Callable):
        NUM_OUTPUT_RULES[name] = fn
        return fn
    return deco


# canonical op name -> fn(input_shapes, attrs) -> {input_index: shape}.
# The FInferShape *backward fill* of the reference registry
# (include/mxnet/op_attr_types.h FInferShape; e.g. fully_connected.cc
# derives weight=(num_hidden, in_units) from the data shape): given the
# known input shapes (None for unknown), a rule returns shapes for the
# op's parameter inputs so symbols with undeclared parameter shapes can
# still be inferred (BucketingModule on unseen buckets depends on this).
PARAM_SHAPE_RULES: Dict[str, Callable] = {}


def register_param_shapes(name: str):
    """Attach a parameter-shape backward-fill rule to a registered op."""

    def deco(fn: Callable):
        PARAM_SHAPE_RULES[name] = fn
        return fn

    return deco


def get_param_shape_rule(name: str) -> Optional[Callable]:
    op = REGISTRY.get(name)
    return PARAM_SHAPE_RULES.get(op.name if op is not None else name)


def get_op(name: str) -> Op:
    if name not in REGISTRY:
        raise KeyError("Operator %s is not registered" % name)
    return REGISTRY[name]


def list_ops() -> List[str]:
    return sorted(REGISTRY)


def invoke(name: str, *args, **kwargs):
    """Invoke a registered op by name (symbol executor / C-ABI entry point)."""
    return get_op(name).wrapper(*args, **kwargs)


def attach_methods(cls=NDArray):
    """Attach registered ops marked ``as_method`` as NDArray methods, mirroring the
    reference's generated method surface (python/mxnet/ndarray/register.py)."""
    for key, op in list(REGISTRY.items()):
        if not op.as_method:
            continue
        if getattr(cls, key, None) is not None:
            continue  # don't clobber hand-written methods

        def make(opw):
            def method(self, *args, **kwargs):
                return opw(self, *args, **kwargs)
            return method

        setattr(cls, key, make(op.wrapper))


def describe(name: str) -> dict:
    """Parameter reflection for a registered op — the dmlc::Parameter /
    DMLC_DECLARE_FIELD analog (SURVEY §5 config system): the reference
    generates Python signatures + docstrings from each op's declared param
    struct; here the op IS a Python function, so its signature is the
    declaration. Returns {"name", "doc", "arguments": [...],
    "attributes": [{"name", "default"}...]}."""
    op = get_op(name)
    sig = inspect.signature(op.fn)
    arguments = []
    attributes = []
    for pname, p in sig.parameters.items():
        if p.kind in (inspect.Parameter.VAR_POSITIONAL,
                      inspect.Parameter.VAR_KEYWORD):
            arguments.append({"name": pname, "variadic": True})
        elif p.default is inspect.Parameter.empty:
            arguments.append({"name": pname})
        else:
            attributes.append({"name": pname, "default": p.default})
    return {"name": op.name, "aliases": list(op.aliases),
            "doc": op.doc, "arguments": arguments,
            "attributes": attributes}
