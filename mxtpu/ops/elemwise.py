"""Elementwise unary/binary/scalar/logic op families.

Reference: src/operator/tensor/elemwise_unary_op_basic.cc, elemwise_binary_op*.cc,
elemwise_binary_broadcast_op*.cc, elemwise_binary_scalar_op*.cc and the scalar-math
functor zoo in src/operator/mshadow_op.h. Each reference op is an (-inl.h, .cc, .cu)
kernel triple; here each is a one-line XLA lowering — fusion is the compiler's job
(the reference needed hand-bulked engine segments for the same effect,
src/executor/graph_executor.cc:1187).

MXNet distinguishes ``elemwise_*`` (same-shape) from ``broadcast_*`` (numpy broadcast);
both map to the same XLA HLO here, and the scalar variants (``_plus_scalar`` …) are the
same lowering with a python scalar operand.
"""
from __future__ import annotations

import jax.numpy as jnp
import jax

from .registry import register

_f32 = jnp.float32


def _u(name, fn, aliases=(), as_method=True):
    """Register a unary op."""
    return register(name, aliases=aliases, as_method=as_method)(fn)


# ---------------------------------------------------------------- unary math
abs_ = _u("abs", lambda x: jnp.abs(x))
sign = _u("sign", lambda x: jnp.sign(x))
rint = _u("rint", lambda x: jnp.rint(x))
round_ = _u("round", lambda x: jnp.round(x))
ceil = _u("ceil", lambda x: jnp.ceil(x))
floor = _u("floor", lambda x: jnp.floor(x))
trunc = _u("trunc", lambda x: jnp.trunc(x))
fix = _u("fix", lambda x: jnp.fix(x))
square = _u("square", lambda x: jnp.square(x))
sqrt = _u("sqrt", lambda x: jnp.sqrt(x))
rsqrt = _u("rsqrt", lambda x: jax.lax.rsqrt(x))
cbrt = _u("cbrt", lambda x: jnp.cbrt(x))
rcbrt = _u("rcbrt", lambda x: 1.0 / jnp.cbrt(x))
exp = _u("exp", lambda x: jnp.exp(x))
log = _u("log", lambda x: jnp.log(x))
log10 = _u("log10", lambda x: jnp.log10(x))
log2 = _u("log2", lambda x: jnp.log2(x))
log1p = _u("log1p", lambda x: jnp.log1p(x))
expm1 = _u("expm1", lambda x: jnp.expm1(x))
gamma = _u("gamma", lambda x: jnp.exp(jax.scipy.special.gammaln(x)))
gammaln = _u("gammaln", lambda x: jax.scipy.special.gammaln(x))
erf = _u("erf", lambda x: jax.scipy.special.erf(x))
erfinv = _u("erfinv", lambda x: jax.scipy.special.erfinv(x))
sin = _u("sin", lambda x: jnp.sin(x))
cos = _u("cos", lambda x: jnp.cos(x))
tan = _u("tan", lambda x: jnp.tan(x))
arcsin = _u("arcsin", lambda x: jnp.arcsin(x))
arccos = _u("arccos", lambda x: jnp.arccos(x))
arctan = _u("arctan", lambda x: jnp.arctan(x))
sinh = _u("sinh", lambda x: jnp.sinh(x))
cosh = _u("cosh", lambda x: jnp.cosh(x))
tanh = _u("tanh", lambda x: jnp.tanh(x))
arcsinh = _u("arcsinh", lambda x: jnp.arcsinh(x))
arccosh = _u("arccosh", lambda x: jnp.arccosh(x))
arctanh = _u("arctanh", lambda x: jnp.arctanh(x))
degrees = _u("degrees", lambda x: jnp.degrees(x))
radians = _u("radians", lambda x: jnp.radians(x))
reciprocal = _u("reciprocal", lambda x: 1.0 / x)
negative = _u("negative", lambda x: jnp.negative(x))
logical_not = _u("logical_not", lambda x: jnp.logical_not(x).astype(_f32))
relu = _u("relu", lambda x: jnp.maximum(x, 0))
sigmoid = _u("sigmoid", lambda x: jax.nn.sigmoid(x))
softsign = _u("softsign", lambda x: x / (1.0 + jnp.abs(x)))
identity = _u("identity", lambda x: x, aliases=("_copy",), as_method=False)


@register("BlockGrad", aliases=("stop_gradient",), as_method=True)
def BlockGrad(x):
    """Stop gradient flow (ref: src/operator/tensor/elemwise_unary_op_basic.cc
    BlockGrad; MakeLoss sibling)."""
    return jax.lax.stop_gradient(x)


@register("make_loss", aliases=("MakeLoss",))
def make_loss(x, grad_scale=1.0, **_ignored):
    """Head marker whose gradient is ``grad_scale`` (ref: src/operator/make_loss.cc)."""
    @jax.custom_vjp
    def _loss(v):
        return v

    def _fwd(v):
        return v, None

    def _bwd(_, g):
        return (jnp.full_like(g, grad_scale),)

    _loss.defvjp(_fwd, _bwd)
    return _loss(x)


# ---------------------------------------------------------------- binary
def _b(name, fn, aliases=(), as_method=False):
    return register(name, aliases=aliases, as_method=as_method)(fn)


broadcast_add = _b("broadcast_add", lambda a, b: jnp.add(a, b),
                   aliases=("elemwise_add", "_plus_scalar", "_add", "_grad_add"))
broadcast_sub = _b("broadcast_sub", lambda a, b: jnp.subtract(a, b),
                   aliases=("elemwise_sub", "_minus_scalar", "_sub"))
broadcast_mul = _b("broadcast_mul", lambda a, b: jnp.multiply(a, b),
                   aliases=("elemwise_mul", "_mul_scalar", "_mul"))
broadcast_div = _b("broadcast_div", lambda a, b: jnp.divide(a, b),
                   aliases=("elemwise_div", "_div_scalar", "_div"))
broadcast_mod = _b("broadcast_mod", lambda a, b: jnp.mod(a, b),
                   aliases=("_mod_scalar", "_mod"))
_rmod_scalar = _b("_rmod_scalar", lambda a, b: jnp.mod(b, a))
broadcast_power = _b("broadcast_power", lambda a, b: jnp.power(a, b),
                     aliases=("_power_scalar", "_power"))
broadcast_maximum = _b("broadcast_maximum", lambda a, b: jnp.maximum(a, b),
                       aliases=("_maximum_scalar", "_maximum", "maximum"))
broadcast_minimum = _b("broadcast_minimum", lambda a, b: jnp.minimum(a, b),
                       aliases=("_minimum_scalar", "_minimum", "minimum"))
broadcast_hypot = _b("broadcast_hypot", lambda a, b: jnp.hypot(a, b),
                     aliases=("_hypot", "_hypot_scalar"))
_rminus_scalar = _b("_rminus_scalar", lambda a, b: jnp.subtract(b, a))
_rdiv_scalar = _b("_rdiv_scalar", lambda a, b: jnp.divide(b, a))
_rpower_scalar = _b("_rpower_scalar", lambda a, b: jnp.power(b, a))
arctan2 = _b("arctan2", lambda a, b: jnp.arctan2(a, b), aliases=("_arctan2",))
ldexp = _b("ldexp", lambda a, b: a * jnp.power(2.0, b))

broadcast_equal = _b("broadcast_equal", lambda a, b: jnp.equal(a, b).astype(_f32),
                     aliases=("_equal", "_equal_scalar"))
broadcast_not_equal = _b("broadcast_not_equal", lambda a, b: jnp.not_equal(a, b).astype(_f32),
                         aliases=("_not_equal", "_not_equal_scalar"))
broadcast_greater = _b("broadcast_greater", lambda a, b: jnp.greater(a, b).astype(_f32),
                       aliases=("_greater", "_greater_scalar"))
broadcast_greater_equal = _b("broadcast_greater_equal",
                             lambda a, b: jnp.greater_equal(a, b).astype(_f32),
                             aliases=("_greater_equal", "_greater_equal_scalar"))
broadcast_lesser = _b("broadcast_lesser", lambda a, b: jnp.less(a, b).astype(_f32),
                      aliases=("_lesser", "_lesser_scalar"))
broadcast_lesser_equal = _b("broadcast_lesser_equal",
                            lambda a, b: jnp.less_equal(a, b).astype(_f32),
                            aliases=("_lesser_equal", "_lesser_equal_scalar"))
broadcast_logical_and = _b("broadcast_logical_and",
                           lambda a, b: jnp.logical_and(a, b).astype(_f32),
                           aliases=("_logical_and", "_logical_and_scalar"))
broadcast_logical_or = _b("broadcast_logical_or",
                          lambda a, b: jnp.logical_or(a, b).astype(_f32),
                          aliases=("_logical_or", "_logical_or_scalar"))
broadcast_logical_xor = _b("broadcast_logical_xor",
                           lambda a, b: jnp.logical_xor(a, b).astype(_f32),
                           aliases=("_logical_xor", "_logical_xor_scalar"))


@register("smooth_l1")
def smooth_l1(x, scalar=1.0):
    """Huber-like smooth L1 (ref: src/operator/tensor/elemwise_binary_scalar_op_extended.cc
    smooth_l1; mshadow_op.h smooth_l1_loss)."""
    s2 = scalar * scalar
    ax = jnp.abs(x)
    return jnp.where(ax < 1.0 / s2, 0.5 * s2 * jnp.square(x), ax - 0.5 / s2)


@register("clip", as_method=True)
def clip(x, a_min=None, a_max=None):
    """Clamp (ref: src/operator/tensor/matrix_op.cc clip). Gradient is zero outside
    the interval, matching the reference's clip backward."""
    return jnp.clip(x, a_min, a_max)


@register("elemwise_sum", aliases=("add_n", "ElementWiseSum"))
def elemwise_sum(*args):
    """Sum of N arrays in one fused HLO (ref: src/ndarray/ndarray.cc:1280
    ElementwiseSum; the engine bulked these — XLA fuses them)."""
    out = args[0]
    for a in args[1:]:
        out = out + a
    return out


@register("where")
def where(condition, x, y):
    """Select by condition (ref: src/operator/tensor/control_flow_op.cc where)."""
    return jnp.where(condition.astype(bool) if condition.dtype != jnp.bool_ else condition, x, y)


@register("cast", aliases=("Cast",), as_method=False)
def cast(x, dtype="float32"):
    from ..ndarray.ndarray import _as_jax_dtype
    return x.astype(_as_jax_dtype(dtype))


@register("hard_sigmoid")
def hard_sigmoid(x, alpha=0.2, beta=0.5):
    """Piecewise-linear sigmoid ``max(0, min(1, alpha*x + beta))``
    (ref: src/operator/tensor/elemwise_unary_op_basic.cc:109 hard_sigmoid,
    HardSigmoidParam alpha=0.2 beta=0.5). Written as nested selects rather
    than clip so the vjp is exactly the reference backward — grad = alpha
    strictly inside the linear band, 0 at and beyond saturation (clip's
    min/max vjp splits the gradient at exact boundary ties)."""
    y = alpha * x + beta
    return jnp.where(y <= 0.0, 0.0, jnp.where(y >= 1.0, 1.0, y))


# ------------------------------------------------------ scatter-family ops
# Reference: src/operator/tensor/elemwise_scatter_op.cc. Semantics: the op
# is applied ONLY at the lhs's stored values when lhs is sparse (the result
# keeps lhs's storage and sparsity pattern — a non-zero-preserving op like
# `+ scalar` deliberately does NOT densify); dense lhs degenerates to the
# ordinary elementwise op. Used by sparse optimizer updates.

def _emit(res, out):
    """Write a possibly-sparse result into ``out`` via copyto (which moves
    aux indices/shape along with values — out._set_data alone would leave a
    sparse out's indices stale) or return it."""
    if out is None:
        return res
    return res.copyto(out)


def _scatter_scalar(name, jfn):
    @register(name, wrap=False)
    def fn(lhs, scalar=0.0, out=None, **_ig):
        from ..ndarray.ndarray import _apply as _ap
        from ..ndarray.sparse import BaseSparseNDArray
        vals = _ap(lambda a: jfn(a, scalar), (lhs,), name=name)
        if isinstance(lhs, BaseSparseNDArray):
            res = lhs._replace_values(vals._data)
            res._ag_entry = vals._ag_entry
        else:
            res = vals
        return _emit(res, out)
    fn.__name__ = name
    return fn


_scatter_plus_scalar = _scatter_scalar("_scatter_plus_scalar",
                                       lambda a, s: jnp.add(a, s))
_scatter_minus_scalar = _scatter_scalar("_scatter_minus_scalar",
                                        lambda a, s: jnp.subtract(a, s))


@register("_scatter_elemwise_div", wrap=False)
def _scatter_elemwise_div(lhs, rhs, out=None, **_ig):
    """Divide, evaluated only at lhs's stored rows when lhs is row_sparse
    (ref: elemwise_scatter_op.cc:69): result rows = lhs.values / rhs[row_ids],
    keeping lhs's sparsity — the dense rhs never materializes a dense lhs."""
    from ..ndarray.ndarray import _apply as _ap
    from ..ndarray.sparse import BaseSparseNDArray, RowSparseNDArray
    if isinstance(rhs, BaseSparseNDArray):
        rhs = rhs.todense()  # storage fallback: rhs is read densely
    if isinstance(lhs, RowSparseNDArray):
        idx = lhs._aux["indices"]
        vals = _ap(lambda v, d: v / d[idx], (lhs, rhs),
                   name="_scatter_elemwise_div")
        res = lhs._replace_values(vals._data)
        res._ag_entry = vals._ag_entry
    else:
        if isinstance(lhs, BaseSparseNDArray):
            # CSR lhs: the reference's storage rule falls back to dense
            # (its values buffer is 1-D, not row-addressable)
            lhs = lhs.todense()
        res = _ap(jnp.divide, (lhs, rhs), name="_scatter_elemwise_div")
    return _emit(res, out)
