"""Operator library: registry + op families.

The TPU-native replacement for src/operator/ (~110k LoC of kernel triples in the
reference): op *definitions* here, kernels from XLA/Pallas lowering (SURVEY §2.2 "→
TPU"). Importing this package populates the registry and attaches NDArray methods,
playing the role of the reference's import-time Python codegen from the C op registry
(python/mxnet/ndarray/register.py:143-157).
"""
from . import elemwise  # noqa: F401
from . import reduce  # noqa: F401
from . import matrix  # noqa: F401
from . import init_ops  # noqa: F401
from . import nn  # noqa: F401
from .registry import REGISTRY, attach_methods, get_op, invoke, list_ops, register

# families registered after the core five (import order only matters for aliases)
from . import random_ops  # noqa: F401
from . import linalg_ops  # noqa: F401
from . import rnn_ops  # noqa: F401
from . import contrib_ops  # noqa: F401
from . import optimizer_ops  # noqa: F401
from . import control_flow  # noqa: F401
from . import ctc  # noqa: F401
from . import custom  # noqa: F401
from . import quantization  # noqa: F401
from . import image_ops  # noqa: F401
from . import subgraph_ops  # noqa: F401
from . import legacy_vision  # noqa: F401

attach_methods()
