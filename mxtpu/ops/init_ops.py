"""Array-creation ops (ref: src/operator/tensor/init_op.cc — zeros/ones/full/arange/
linspace/eye and the *_like family). These take no NDArray inputs, so they return fresh
arrays with no tape linkage."""
from __future__ import annotations

import jax.numpy as jnp

from ..base import Context
from ..ndarray.ndarray import NDArray, _as_jax_dtype
from .registry import register


def _place(data, ctx):
    if ctx is not None:
        import jax
        data = jax.device_put(data, Context(ctx).jax_device() if not isinstance(ctx, Context) else ctx.jax_device())
    return NDArray(data)


@register("zeros", aliases=("_zeros",), wrap=False)
def zeros(shape, ctx=None, dtype="float32", stype=None, **_ig):
    if isinstance(shape, int):
        shape = (shape,)
    return _place(jnp.zeros(tuple(shape), _as_jax_dtype(dtype)), ctx)


@register("ones", aliases=("_ones",), wrap=False)
def ones(shape, ctx=None, dtype="float32", **_ig):
    if isinstance(shape, int):
        shape = (shape,)
    return _place(jnp.ones(tuple(shape), _as_jax_dtype(dtype)), ctx)


@register("full", aliases=("_full",), wrap=False)
def full(shape, val=0.0, ctx=None, dtype="float32", **_ig):
    if isinstance(shape, int):
        shape = (shape,)
    return _place(jnp.full(tuple(shape), val, _as_jax_dtype(dtype)), ctx)


@register("empty", wrap=False)
def empty(shape, ctx=None, dtype="float32"):
    return zeros(shape, ctx=ctx, dtype=dtype)


@register("arange", aliases=("_arange",), wrap=False)
def arange(start, stop=None, step=1.0, repeat=1, ctx=None, dtype="float32", **_ig):
    arr = jnp.arange(start, stop, step, dtype=_as_jax_dtype(dtype))
    if repeat > 1:
        arr = jnp.repeat(arr, repeat)
    return _place(arr, ctx)


@register("linspace", wrap=False)
def linspace(start, stop, num, endpoint=True, ctx=None, dtype="float32"):
    return _place(jnp.linspace(start, stop, num, endpoint=endpoint,
                               dtype=_as_jax_dtype(dtype)), ctx)


@register("eye", aliases=("_eye",), wrap=False)
def eye(N, M=0, k=0, ctx=None, dtype="float32", **_ig):
    return _place(jnp.eye(N, M if M else None, k=k, dtype=_as_jax_dtype(dtype)), ctx)


@register("zeros_like", as_method=False)
def zeros_like(x):
    return jnp.zeros_like(x)


@register("ones_like", as_method=False)
def ones_like(x):
    return jnp.ones_like(x)


@register("full_like")
def full_like(x, fill_value=0.0):
    return jnp.full_like(x, fill_value)


@register("arange_like")
def arange_like(x, start=0.0, step=1.0, repeat=1, axis=None):
    if axis is None:
        n = x.size
        shape = x.shape
    else:
        n = x.shape[axis]
        shape = (n,)
    arr = jnp.arange(start, start + step * n, step, dtype=jnp.float32)[:n]
    return jnp.reshape(arr, shape) if axis is None else arr
