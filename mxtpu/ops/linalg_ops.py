"""Linear-algebra op family (ref: src/operator/tensor/la_op.cc + c_lapack_api.h —
LAPACK-on-CPU/cuSOLVER-on-GPU in the reference; here XLA's native decompositions,
which lower to MXU matmuls + host offload where required)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register


@register("linalg_gemm")
def linalg_gemm(A, B, C, transpose_a=False, transpose_b=False, alpha=1.0, beta=1.0,
                axis=-2):
    a = jnp.swapaxes(A, -1, -2) if transpose_a else A
    b = jnp.swapaxes(B, -1, -2) if transpose_b else B
    return alpha * jnp.matmul(a, b) + beta * C


@register("linalg_gemm2")
def linalg_gemm2(A, B, transpose_a=False, transpose_b=False, alpha=1.0, axis=-2):
    a = jnp.swapaxes(A, -1, -2) if transpose_a else A
    b = jnp.swapaxes(B, -1, -2) if transpose_b else B
    return alpha * jnp.matmul(a, b)


@register("linalg_potrf")
def linalg_potrf(A):
    """Cholesky (ref: la_op.cc potrf)."""
    return jnp.linalg.cholesky(A)


@register("linalg_potri")
def linalg_potri(A):
    """Inverse from Cholesky factor (ref: la_op.cc potri)."""
    eye = jnp.broadcast_to(jnp.eye(A.shape[-1], dtype=A.dtype), A.shape)
    inv_l = jax.scipy.linalg.solve_triangular(A, eye, lower=True)
    return jnp.matmul(jnp.swapaxes(inv_l, -1, -2), inv_l)


@register("linalg_trmm")
def linalg_trmm(A, B, transpose=False, rightside=False, lower=True, alpha=1.0):
    a = jnp.swapaxes(A, -1, -2) if transpose else A
    return alpha * (jnp.matmul(B, a) if rightside else jnp.matmul(a, B))


@register("linalg_trsm")
def linalg_trsm(A, B, transpose=False, rightside=False, lower=True, alpha=1.0):
    if rightside:
        # X A = alpha B  =>  A^T X^T = alpha B^T; transposing flips lower/upper
        a = jnp.swapaxes(A, -1, -2)
        eff_lower = lower if transpose else not lower
        x = jax.scipy.linalg.solve_triangular(
            a if not transpose else A, jnp.swapaxes(alpha * B, -1, -2),
            lower=eff_lower, trans=0)
        return jnp.swapaxes(x, -1, -2)
    return jax.scipy.linalg.solve_triangular(A, alpha * B, lower=lower,
                                             trans=1 if transpose else 0)


@register("linalg_sumlogdiag")
def linalg_sumlogdiag(A):
    return jnp.sum(jnp.log(jnp.diagonal(A, axis1=-2, axis2=-1)), axis=-1)


@register("linalg_syrk")
def linalg_syrk(A, transpose=False, alpha=1.0):
    a = jnp.swapaxes(A, -1, -2) if transpose else A
    return alpha * jnp.matmul(a, jnp.swapaxes(a, -1, -2))


@register("linalg_gelqf", num_outputs=2)
def linalg_gelqf(A):
    """LQ factorization (ref: la_op.cc gelqf). A = L Q with Q orthonormal rows."""
    q, r = jnp.linalg.qr(jnp.swapaxes(A, -1, -2), mode="reduced")
    return [jnp.swapaxes(r, -1, -2), jnp.swapaxes(q, -1, -2)]


@register("linalg_syevd", num_outputs=2)
def linalg_syevd(A):
    """Symmetric eigendecomposition (ref: la_op.cc syevd): returns (U, L) with
    A = U^T diag(L) U."""
    w, v = jnp.linalg.eigh(A)
    return [jnp.swapaxes(v, -1, -2), w]


@register("linalg_makediag")
def linalg_makediag(A, offset=0):
    return jnp.zeros(A.shape + (A.shape[-1],), A.dtype) + jnp.expand_dims(A, -2) * \
        jnp.eye(A.shape[-1], dtype=A.dtype)


@register("linalg_extractdiag")
def linalg_extractdiag(A, offset=0):
    return jnp.diagonal(A, offset=offset, axis1=-2, axis2=-1)


@register("linalg_inverse", aliases=("inverse",))
def linalg_inverse(A):
    return jnp.linalg.inv(A)


@register("linalg_det", aliases=("det",))
def linalg_det(A):
    return jnp.linalg.det(A)


@register("linalg_slogdet", aliases=("slogdet",), num_outputs=2)
def linalg_slogdet(A):
    sign, ld = jnp.linalg.slogdet(A)
    return [sign, ld]
