"""Neural-network ops: the FLOP-carrying layer of the framework.

Reference: src/operator/nn/* — each op is an (-inl.h, .cc, .cu) kernel triple with
cuDNN/MKL-DNN backends and an autotuned algo registry (cudnn_algoreg-inl.h).

TPU-native re-design: every op lowers to the XLA HLO that maps onto the MXU/VPU —
``lax.conv_general_dilated`` (MXU), ``lax.reduce_window`` (VPU), ``jax.nn.*`` — and
XLA's own autotuner/fusion replaces the cuDNN algo registry and MKL-DNN format
machinery. Layouts: the reference is NCHW-only; here every spatial op takes a
``layout`` attr and NHWC is preferred on TPU (channels-last vectorizes on the 8x128
VPU and feeds the MXU without transposes) while NCHW remains the API default for
reference parity — XLA inserts the transposes when needed.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .. import autograd
from ..random import next_key
from .conv_acc import conv_fast
from .precision_util import dot_acc, mxu_precision
from .registry import register


def _pair(v, n=2):
    if v is None:
        return (1,) * n
    if isinstance(v, int):
        return (v,) * n
    v = tuple(v)
    if len(v) == 1:
        return v * n
    return v


# ------------------------------------------------------------- dense / conv
@register("FullyConnected", aliases=("fully_connected",))
def FullyConnected(data, weight, bias=None, num_hidden=None, no_bias=False,
                   flatten=True):
    """y = x W^T + b (ref: src/operator/nn/fully_connected.cc:239-328).

    Weight layout (num_hidden, in_units) matches the reference exactly so
    checkpoints are interchangeable. bf16 inputs run one-pass on the MXU
    with an f32 accumulator output cast back to bf16 in the epilogue —
    the measurably fastest v5e schedule (tools/perf_peak.py: 140 vs 102
    TFLOP/s for the bf16-out form) and exact accumulation for free; f32
    inputs get true-f32 contractions via the global
    jax_default_matmul_precision setting (mxtpu/__init__.py). See
    precision_util.dot_acc.
    """
    x = data
    if flatten and x.ndim > 2:
        x = jnp.reshape(x, (x.shape[0], -1))
    y = dot_acc(x, weight, (((x.ndim - 1,), (1,)), ((), ())))
    if bias is not None and not no_bias:
        y = y + bias
    return y


_LAYOUTS = {
    1: {"NCW": ("NCH", "OIH", "NCH"), "NWC": ("NHC", "HIO", "NHC")},
}


def _conv_dims(ndim, layout):
    """Dimension-number strings for lax.conv_general_dilated."""
    if ndim == 1:
        if layout in (None, "NCW"):
            return ("NCH", "OIH", "NCH")
        return ("NHC", "HIO", "NHC")
    if ndim == 2:
        if layout in (None, "NCHW"):
            return ("NCHW", "OIHW", "NCHW")
        return ("NHWC", "HWIO", "NHWC")
    if ndim == 3:
        if layout in (None, "NCDHW"):
            return ("NCDHW", "OIDHW", "NCDHW")
        return ("NDHWC", "DHWIO", "NDHWC")
    raise ValueError("unsupported conv ndim %d" % ndim)


@register("Convolution", aliases=("convolution",))
def Convolution(data, weight, bias=None, kernel=None, stride=None, dilate=None,
                pad=None, num_filter=None, num_group=1, no_bias=False, layout=None,
                workspace=None, cudnn_tune=None, cudnn_off=None):
    """N-D convolution (ref: src/operator/nn/convolution.cc; CUDA path
    src/operator/nn/convolution.cu + cudnn wrappers). One HLO ConvGeneralDilated;
    grouped/depthwise via feature_group_count (the reference needed a dedicated
    TF-derived depthwise kernel, depthwise_convolution_tf.cuh — here it's the same
    HLO and XLA picks the kernel). bf16 operands take the f32-accumulate
    custom-vjp fast path (conv_acc.py); MXU-underfilled NHWC shapes (the
    stem/1x1/small-C classes PERF.md attributes ~78%% of the ResNet step
    to) route to the Pallas implicit-GEMM kernel when MXTPU_PALLAS_CONV
    is on (pallas/conv.py), with the bias riding its fused epilogue —
    the bias is handed to conv_fast so every dispatch path owns it."""
    ndim = data.ndim - 2
    kernel = _pair(kernel, ndim)
    stride = _pair(stride, ndim)
    dilate = _pair(dilate, ndim)
    pad = _pair(pad, ndim) if pad is not None else (0,) * ndim
    dims = _conv_dims(ndim, layout)
    return conv_fast(
        data, weight,
        strides=stride,
        padding=[(p, p) for p in pad],
        lhs_dilation=(1,) * ndim,
        rhs_dilation=dilate,
        dims=dims,
        groups=num_group,
        bias=bias if (bias is not None and not no_bias) else None,
    )


@register("Deconvolution", aliases=("deconvolution",))
def Deconvolution(data, weight, bias=None, kernel=None, stride=None, dilate=None,
                  pad=None, adj=None, target_shape=None, num_filter=None, num_group=1,
                  no_bias=True, layout=None, workspace=None, cudnn_tune=None,
                  cudnn_off=None):
    """Transposed convolution (ref: src/operator/nn/deconvolution.cc). Implemented as
    the gradient of Convolution wrt data — lhs-dilated ConvGeneralDilated."""
    ndim = data.ndim - 2
    kernel = _pair(kernel, ndim)
    stride = _pair(stride, ndim)
    dilate = _pair(dilate, ndim)
    pad = _pair(pad, ndim) if pad is not None else (0,) * ndim
    adj = _pair(adj, ndim) if adj is not None else (0,) * ndim
    dims = _conv_dims(ndim, layout)
    channels_last = dims[0][-1] == "C"
    # weight layout (in, out/g, *k) per reference; flip spatial + swap io for transpose
    spatial_axes = tuple(range(2, 2 + ndim)) if not channels_last else tuple(range(0, ndim))
    if channels_last:
        w = jnp.flip(weight, axis=spatial_axes)
        w = jnp.swapaxes(w, -1, -2)
    else:
        w = jnp.flip(weight, axis=spatial_axes)
        w = jnp.swapaxes(w, 0, 1)
    padding = []
    for i in range(ndim):
        k = (kernel[i] - 1) * dilate[i]
        padding.append((k - pad[i], k - pad[i] + adj[i]))
    return conv_fast(
        data, w,
        strides=(1,) * ndim,
        padding=padding,
        lhs_dilation=stride,
        rhs_dilation=dilate,
        dims=dims,
        groups=num_group,
        bias=bias if (bias is not None and not no_bias) else None,
    )


# ------------------------------------------------------------------ pooling
@register("Pooling", aliases=("pooling",))
def Pooling(data, kernel=None, pool_type="max", global_pool=False, stride=None,
            pad=None, pooling_convention="valid", count_include_pad=True,
            layout=None, cudnn_off=None, p_value=None):
    """Spatial pooling (ref: src/operator/nn/pooling.cc + pool.cuh hand kernels).
    One HLO ReduceWindow; 'full' (ceil) convention handled via asymmetric padding."""
    ndim = data.ndim - 2
    channels_last = layout is not None and layout.endswith("C")
    sp = tuple(range(1, 1 + ndim)) if channels_last else tuple(range(2, 2 + ndim))
    if global_pool:
        if pool_type == "max":
            return jnp.max(data, axis=sp, keepdims=True)
        if pool_type in ("avg", "sum"):
            r = jnp.mean if pool_type == "avg" else jnp.sum
            return r(data, axis=sp, keepdims=True)
        if pool_type == "lp":
            p = p_value or 2
            return jnp.power(jnp.sum(jnp.power(jnp.abs(data), p), axis=sp, keepdims=True), 1.0 / p)
    kernel = _pair(kernel, ndim)
    stride = _pair(stride, ndim) if stride is not None else (1,) * ndim
    pad = _pair(pad, ndim) if pad is not None else (0,) * ndim

    window = [1] * data.ndim
    strides = [1] * data.ndim
    padding = [(0, 0)] * data.ndim
    for i, a in enumerate(sp):
        window[a] = kernel[i]
        strides[a] = stride[i]
        lo = hi = pad[i]
        if pooling_convention == "full":
            size = data.shape[a]
            out_sz = -(-(size + 2 * pad[i] - kernel[i]) // stride[i]) + 1  # ceil
            needed = (out_sz - 1) * stride[i] + kernel[i] - size - pad[i]
            hi = max(hi, needed)
        padding[a] = (lo, hi)

    if pool_type == "max":
        init = -jnp.inf if jnp.issubdtype(data.dtype, jnp.floating) else jnp.iinfo(data.dtype).min
        return lax.reduce_window(data, init, lax.max,
                                 window, strides, padding)
    if pool_type in ("avg", "sum"):
        s = lax.reduce_window(data, 0, lax.add,
                              window, strides, padding)
        if pool_type == "sum":
            return s
        if count_include_pad:
            denom = 1
            for i in range(ndim):
                denom *= kernel[i]
            return s / denom
        ones = jnp.ones(data.shape, data.dtype)
        cnt = lax.reduce_window(ones, 0, lax.add,
                                window, strides, padding)
        return s / cnt
    if pool_type == "lp":
        p = p_value or 2
        s = lax.reduce_window(jnp.power(jnp.abs(data), p), 0,
                              lax.add, window, strides, padding)
        return jnp.power(s, 1.0 / p)
    raise ValueError("unknown pool_type " + pool_type)


@register("UpSampling")
def UpSampling(*data, scale=1, sample_type="nearest", num_args=1, num_filter=0,
               multi_input_mode="concat", workspace=None):
    """Ref: src/operator/nn/upsampling.cc (nearest; bilinear via Deconvolution)."""
    x = data[0]
    n, c, h, w = x.shape
    if sample_type == "nearest":
        out = jnp.repeat(jnp.repeat(x, scale, axis=2), scale, axis=3)
        return out
    # bilinear
    out = jax.image.resize(x, (n, c, h * scale, w * scale), method="bilinear")
    return out


# ----------------------------------------------------------------- softmax
@register("softmax", aliases=("Softmax",), as_method=True)
def softmax(x, axis=-1, temperature=None, length=None, **_ig):
    if temperature is not None and temperature != 1.0:
        x = x / temperature
    if length is not None:
        mask = jnp.arange(x.shape[axis]) < jnp.expand_dims(length.astype(jnp.int32), -1)
        x = jnp.where(mask, x, -jnp.inf)
    return jax.nn.softmax(x, axis=axis)


@register("log_softmax", as_method=True)
def log_softmax(x, axis=-1, temperature=None, **_ig):
    if temperature is not None and temperature != 1.0:
        x = x / temperature
    return jax.nn.log_softmax(x, axis=axis)


@register("softmin")
def softmin(x, axis=-1, **_ig):
    return jax.nn.softmax(-x, axis=axis)


@register("SoftmaxActivation")
def SoftmaxActivation(x, mode="instance"):
    """Deprecated alias family (ref: src/operator/nn/softmax_activation.cc)."""
    if mode == "channel":
        return jax.nn.softmax(x, axis=1)
    return jax.nn.softmax(x, axis=-1)


@register("SoftmaxOutput", aliases=("softmax_output",))
def SoftmaxOutput(data, label, grad_scale=1.0, ignore_label=-1.0,
                  multi_output=False, use_ignore=False, preserve_shape=False,
                  normalization="null", out_grad=False, smooth_alpha=0.0):
    """Softmax with implicit cross-entropy gradient (ref: src/operator/softmax_output.cc).

    Forward returns softmax(data); the custom vjp makes d(data) = (p - onehot(label))
    * grad_scale exactly as the reference's fused backward kernel, including
    ignore_label masking and batch/valid normalization.
    """
    axis = 1 if multi_output else -1

    @jax.custom_vjp
    def _so(d, lab):
        return jax.nn.softmax(d, axis=axis)

    def _fwd(d, lab):
        p = jax.nn.softmax(d, axis=axis)
        return p, (p, lab)

    def _bwd(res, g):
        p, lab = res
        li = lab.astype(jnp.int32)
        nclass = p.shape[axis]
        oh = jax.nn.one_hot(li, nclass, axis=axis, dtype=p.dtype)
        if smooth_alpha:
            oh = oh * (1.0 - smooth_alpha) + smooth_alpha / (nclass - 1) * (1.0 - oh)
        grad = p - oh
        if use_ignore:
            valid = (lab != ignore_label).astype(p.dtype)
            grad = grad * jnp.expand_dims(valid, axis=axis)
        scale = grad_scale
        if normalization == "batch":
            scale = scale / lab.shape[0]
        elif normalization == "valid" and use_ignore:
            nvalid = jnp.maximum(jnp.sum(lab != ignore_label), 1)
            grad = grad / nvalid.astype(p.dtype)
        return (grad * scale, jnp.zeros_like(lab))

    _so.defvjp(_fwd, _bwd)
    return _so(data, label)


# ------------------------------------------------------------- activations
@register("Activation", aliases=("activation",))
def Activation(x, act_type="relu"):
    """Ref: src/operator/nn/activation.cc."""
    if act_type == "relu":
        return jnp.maximum(x, 0)
    if act_type == "sigmoid":
        return jax.nn.sigmoid(x)
    if act_type == "tanh":
        return jnp.tanh(x)
    if act_type == "softrelu":
        return jax.nn.softplus(x)
    if act_type == "softsign":
        return x / (1 + jnp.abs(x))
    raise ValueError("unknown act_type " + act_type)


@register("LeakyReLU", wrap=False)
def LeakyReLU(data, gamma=None, act_type="leaky", slope=0.25, lower_bound=0.125,
              upper_bound=0.334):
    """Leaky/PReLU/ELU/SELU/RReLU family (ref: src/operator/leaky_relu.cc)."""
    from ..ndarray.ndarray import _apply
    if act_type == "rrelu":
        return _rrelu_apply(data, lower_bound, upper_bound)
    if act_type == "prelu":
        return _apply(lambda x, g: _leaky_impl(x, g, "prelu", slope), (data, gamma),
                      name="LeakyReLU")
    return _apply(lambda x: _leaky_impl(x, None, act_type, slope), (data,),
                  name="LeakyReLU")


def _leaky_impl(x, gamma, act_type, slope):
    if act_type == "leaky":
        return jnp.where(x > 0, x, slope * x)
    if act_type == "prelu":
        g = gamma
        if g.ndim < x.ndim and g.ndim == 1:
            g = jnp.reshape(g, (1, -1) + (1,) * (x.ndim - 2))
        return jnp.where(x > 0, x, g * x)
    if act_type == "elu":
        return jnp.where(x > 0, x, slope * (jnp.exp(x) - 1))
    if act_type == "selu":
        alpha, scale = 1.6732632423543772, 1.0507009873554805
        return scale * jnp.where(x > 0, x, alpha * (jnp.exp(x) - 1))
    if act_type == "gelu":
        return jax.nn.gelu(x)
    raise ValueError("unknown act_type " + act_type)


def _bn_onepass():
    """Single-read batch statistics, DEFAULT ON as of round 5: the
    same-session on-chip A/B measured +7.8% end-to-end ResNet-50
    throughput (2331.7 -> 2512.7 img/s, perf_watch.log 16:18) and -9.4%
    on the conv+BN microbench; numerics are pinned eager+hybridized both
    ways (tests/test_precision.py). MXTPU_BN_ONEPASS=0 restores two-pass
    jnp.var stats. Baked into compiled executables: registry.policy_key()
    puts it in jit cache keys so mid-process flips recompile."""
    import os
    return os.environ.get("MXTPU_BN_ONEPASS", "1") == "1"


def bn_batch_stats(xf, red):
    """(mean, var) over axes ``red`` under the active stats policy — THE
    implementation BatchNorm compiles and tools/perf_bn.py measures.
    One-pass mode: E[x] and E[x^2] in one fused read, var clamped >= 0
    (catastrophic-cancellation floor; BN's eps covers the residue)."""
    mean = jnp.mean(xf, axis=red)
    if _bn_onepass():
        var = jnp.maximum(
            jnp.mean(jnp.square(xf), axis=red) - jnp.square(mean), 0.0)
    else:
        var = jnp.var(xf, axis=red)
    return mean, var


@register("BatchNorm", aliases=("batch_norm",), wrap=False)
def BatchNorm(data, gamma, beta, moving_mean, moving_var, eps=1e-3, momentum=0.9,
              fix_gamma=True, use_global_stats=False, output_mean_var=False,
              axis=1, cudnn_off=False):
    """Batch normalization (ref: src/operator/nn/batch_norm.cc).

    Pure-functional: in training mode normalizes by batch stats; the *layer*
    (gluon.nn.BatchNorm) owns the moving-stat update, mirroring how the reference
    mutates aux states inside the kernel while keeping XLA purity. Train/predict
    mode is resolved here at call time (see statefulness note above).
    """
    from ..ndarray.ndarray import _apply
    training = autograd.is_training() and not use_global_stats

    def fn(x, g_, b_, mm, mv):
        shape = [1] * x.ndim
        ax = axis % x.ndim
        shape[ax] = x.shape[ax]
        g = jnp.ones_like(g_) if fix_gamma else g_
        if training:
            red = tuple(i for i in range(x.ndim) if i != ax)
            mean, var = bn_batch_stats(x.astype(jnp.float32), red)
        else:
            mean, var = mm, mv
        inv = lax.rsqrt(var + eps)
        out = (x.astype(jnp.float32) - jnp.reshape(mean, shape)) \
            * jnp.reshape(inv * g.astype(jnp.float32), shape) \
            + jnp.reshape(b_.astype(jnp.float32), shape)
        out = out.astype(x.dtype)
        if output_mean_var:
            return out, mean, var
        return out

    return _apply(fn, (data, gamma, beta, moving_mean, moving_var), name="BatchNorm")


@register("Dropout", aliases=("dropout",), wrap=False)
def Dropout(data, p=0.5, mode="training", axes=(), cudnn_off=None):
    """Inverted dropout (ref: src/operator/nn/dropout.cc). Active only in autograd
    training mode (or mode='always'); RNG key drawn at call time (note above)."""
    from ..ndarray.ndarray import _apply
    if p <= 0 or (mode != "always" and not autograd.is_training()):
        return _apply(lambda x: x, (data,), name="identity")
    key = next_key()
    keep = 1.0 - p

    def fn(x):
        shape = list(x.shape)
        for a in axes or ():
            shape[a] = 1
        mask = jax.random.bernoulli(key, keep, tuple(shape))
        return jnp.where(mask, x / keep, jnp.zeros_like(x))

    return _apply(fn, (data,), name="Dropout")


# NOTE on statefulness: ops whose semantics depend on RNG or train/predict mode
# (Dropout, RReLU, BatchNorm batch-stats) resolve that state *at call time* in an
# unwrapped wrapper, then tape a pure closure. The tape re-executes the closure under
# jax.vjp during backward (recompute-based autograd), so anything resolved inside the
# closure would be re-resolved at backward time — a different dropout mask or the
# wrong BatchNorm branch. This mirrors the reference recording the resolved op state
# (FCreateOpState) on the tape, not the env that produced it.
@register("_rrelu_train", wrap=False)
def _rrelu_apply(data, lower_bound, upper_bound):
    from ..ndarray.ndarray import _apply
    if autograd.is_training():
        key = next_key()

        def fn(x):
            s = jax.random.uniform(key, x.shape, jnp.float32,
                                   lower_bound, upper_bound).astype(x.dtype)
            return jnp.where(x > 0, x, s * x)
    else:
        mid = (lower_bound + upper_bound) / 2.0

        def fn(x):
            return jnp.where(x > 0, x, mid * x)
    return _apply(fn, (data,), name="rrelu")


# ---------------------------------------------------------------- normalize
@register("LayerNorm", aliases=("layer_norm",))
def LayerNorm(data, gamma, beta, axis=-1, eps=1e-5, output_mean_var=False):
    """Ref: src/operator/nn/layer_norm.cc. f32 statistics even for bf16 inputs."""
    x32 = data.astype(jnp.float32)
    mean = jnp.mean(x32, axis=axis, keepdims=True)
    var = jnp.var(x32, axis=axis, keepdims=True)
    inv = lax.rsqrt(var + eps)
    out = ((x32 - mean) * inv).astype(data.dtype)
    shape = [1] * data.ndim
    ax = axis % data.ndim
    shape[ax] = data.shape[ax]
    out = out * jnp.reshape(gamma, shape) + jnp.reshape(beta, shape)
    if output_mean_var:
        return [out, jnp.squeeze(mean, axis), jnp.squeeze(var, axis)]
    return out


@register("InstanceNorm")
def InstanceNorm(data, gamma, beta, eps=1e-3):
    """Ref: src/operator/instance_norm.cc (NCHW; normalize over spatial dims)."""
    red = tuple(range(2, data.ndim))
    mean = jnp.mean(data, axis=red, keepdims=True)
    var = jnp.var(data, axis=red, keepdims=True)
    out = (data - mean) * lax.rsqrt(var + eps)
    shape = (1, -1) + (1,) * (data.ndim - 2)
    return out * jnp.reshape(gamma, shape) + jnp.reshape(beta, shape)


@register("LRN")
def LRN(data, alpha=1e-4, beta=0.75, knorm=2.0, nsize=5):
    """Local response normalization over channels (ref: src/operator/nn/lrn.cc)."""
    sq = jnp.square(data)
    half = nsize // 2
    pad = [(0, 0), (half, half)] + [(0, 0)] * (data.ndim - 2)
    sq = jnp.pad(sq, pad)
    window = [1, nsize] + [1] * (data.ndim - 2)
    s = lax.reduce_window(sq, 0, lax.add,
                          window, [1] * data.ndim, [(0, 0)] * data.ndim)
    return data / jnp.power(knorm + alpha / nsize * s, beta)


# ------------------------------------------------------------ regression/heads
@register("LinearRegressionOutput", aliases=("linear_regression_output",))
def LinearRegressionOutput(data, label, grad_scale=1.0):
    """Identity forward, (pred-label)*scale backward (ref: src/operator/regression_output.cc)."""
    return _regression(data, label, grad_scale, lambda d: d)


@register("LogisticRegressionOutput", aliases=("logistic_regression_output",))
def LogisticRegressionOutput(data, label, grad_scale=1.0):
    return _regression(data, label, grad_scale, jax.nn.sigmoid)


@register("MAERegressionOutput", aliases=("mae_regression_output",))
def MAERegressionOutput(data, label, grad_scale=1.0):
    return _regression(data, label, grad_scale, lambda d: d, grad=jnp.sign)


def _regression(data, label, grad_scale, link, grad=None):
    @jax.custom_vjp
    def _f(d, lab):
        return link(d)

    def _fwd(d, lab):
        return link(d), (link(d), lab)

    def _bwd(res, g):
        p, lab = res
        # the reference reshapes the label to the prediction's shape
        # (regression_output-inl.h) — without this a (N,) label against a
        # (N,1) pred silently broadcasts the grad to (N,N)
        lab_r = jnp.reshape(lab, p.shape)
        diff = grad(p - lab_r) if grad is not None else (p - lab_r)
        num_output = p.size // p.shape[0] if p.ndim > 0 and p.shape[0] \
            else 1
        return (diff * (grad_scale / num_output), jnp.zeros_like(lab))

    _f.defvjp(_fwd, _bwd)
    return _f(data, label)


# ------------------------------------------------------------- sequence ops
def _seq_mask(data, sequence_length, use_sequence_length, value, axis=0):
    if not use_sequence_length or sequence_length is None:
        return data
    maxlen = data.shape[axis]
    steps = jnp.arange(maxlen)
    L = sequence_length.astype(jnp.int32)
    if axis == 0:
        mask = steps[:, None] < L[None, :]
        mask = jnp.reshape(mask, mask.shape + (1,) * (data.ndim - 2))
    else:
        mask = steps[None, :] < L[:, None]
        mask = jnp.reshape(mask, mask.shape + (1,) * (data.ndim - 2))
    return jnp.where(mask, data, jnp.asarray(value, data.dtype))


@register("SequenceMask")
def SequenceMask(data, sequence_length=None, use_sequence_length=False, value=0.0,
                 axis=0):
    """Ref: src/operator/sequence_mask.cc (TNC or NTC via axis)."""
    return _seq_mask(data, sequence_length, use_sequence_length, value, axis)


@register("SequenceLast")
def SequenceLast(data, sequence_length=None, use_sequence_length=False, axis=0):
    """Ref: src/operator/sequence_last.cc."""
    if not use_sequence_length or sequence_length is None:
        idx = [slice(None)] * data.ndim
        idx[axis] = -1
        return data[tuple(idx)]
    L = jnp.maximum(sequence_length.astype(jnp.int32) - 1, 0)
    moved = jnp.moveaxis(data, axis, 0)  # (T, N, ...)
    return jnp.take_along_axis(moved, jnp.reshape(L, (1, -1) + (1,) * (moved.ndim - 2)),
                               axis=0)[0]


@register("SequenceReverse")
def SequenceReverse(data, sequence_length=None, use_sequence_length=False, axis=0):
    """Ref: src/operator/sequence_reverse.cc (time axis 0)."""
    if not use_sequence_length or sequence_length is None:
        return jnp.flip(data, axis=0)
    T = data.shape[0]
    steps = jnp.arange(T)
    L = sequence_length.astype(jnp.int32)  # (N,)
    rev_idx = jnp.where(steps[:, None] < L[None, :], L[None, :] - 1 - steps[:, None],
                        steps[:, None])  # (T, N)
    rev_idx = jnp.reshape(rev_idx, rev_idx.shape + (1,) * (data.ndim - 2))
    return jnp.take_along_axis(data, jnp.broadcast_to(rev_idx, data.shape), axis=0)


# ---------------------------------------------------- parameter shape rules
# FInferShape backward fill (ref: each op's FInferShape in src/operator/nn/*
# deriving weight shapes from the data shape). Consumed by
# Symbol.infer_shape via the registry (mxtpu/ops/registry.py).
from .registry import register_param_shapes  # noqa: E402


@register_param_shapes("FullyConnected")
def _fc_param_shapes(shapes, attrs):
    data = shapes[0]
    if data is None:
        return {}
    num_hidden = int(attrs.get("num_hidden"))
    flatten = attrs.get("flatten", True)
    in_units = 1
    if flatten:
        for s in data[1:]:
            in_units *= s
    else:
        in_units = data[-1]
    out = {1: (num_hidden, in_units)}
    if len(shapes) > 2 and not attrs.get("no_bias", False):
        out[2] = (num_hidden,)
    return out


@register_param_shapes("Convolution")
def _conv_param_shapes(shapes, attrs):
    data = shapes[0]
    if data is None:
        return {}
    ndim = len(data) - 2
    kernel = _pair(attrs.get("kernel"), ndim)
    num_filter = int(attrs.get("num_filter"))
    num_group = int(attrs.get("num_group", 1))
    layout = attrs.get("layout") or "NC" + "DHW"[3 - ndim:]
    channels_last = layout[-1] == "C"
    c_axis = layout.index("C")
    in_ch = data[c_axis]
    if channels_last:
        # weight is HWIO for channels-last (mirrors _conv_dims)
        w = kernel + (in_ch // num_group, num_filter)
    else:
        w = (num_filter, in_ch // num_group) + kernel
    out = {1: w}
    if len(shapes) > 2 and not attrs.get("no_bias", False):
        out[2] = (num_filter,)
    return out


@register_param_shapes("Deconvolution")
def _deconv_param_shapes(shapes, attrs):
    data = shapes[0]
    if data is None:
        return {}
    ndim = len(data) - 2
    kernel = _pair(attrs.get("kernel"), ndim)
    num_filter = int(attrs.get("num_filter"))
    num_group = int(attrs.get("num_group", 1))
    layout = attrs.get("layout") or "NC" + "DHW"[3 - ndim:]
    channels_last = layout[-1] == "C"
    in_ch = data[len(data) - 1 if channels_last else 1]
    if channels_last:
        w = kernel + (num_filter // num_group, in_ch)
    else:
        w = (in_ch, num_filter // num_group) + kernel
    out = {1: w}
    if len(shapes) > 2 and not attrs.get("no_bias", True):
        out[2] = (num_filter,)
    return out


def _channel_param_shapes(shapes, attrs):
    data = shapes[0]
    if data is None:
        return {}
    axis = int(attrs.get("axis", 1)) % len(data)
    c = (data[axis],)
    return {i: c for i in range(1, len(shapes))}


register_param_shapes("BatchNorm")(_channel_param_shapes)
register_param_shapes("InstanceNorm")(_channel_param_shapes)


@register_param_shapes("LayerNorm")
def _ln_param_shapes(shapes, attrs):
    data = shapes[0]
    if data is None:
        return {}
    axis = int(attrs.get("axis", -1)) % len(data)
    c = (data[axis],)
    return {i: c for i in range(1, len(shapes))}


@register_param_shapes("LeakyReLU")
def _leaky_param_shapes(shapes, attrs):
    # only PReLU has a learnable gamma, shaped per-channel (ref:
    # src/operator/leaky_relu-inl.h FInferShape)
    if attrs.get("act_type") != "prelu" or shapes[0] is None \
            or len(shapes) < 2:
        return {}
    return {1: (shapes[0][1],)}
