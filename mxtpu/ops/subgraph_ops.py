"""Ops backing the subgraph/partition framework (mxtpu/symbol/subgraph.py).

Reference: the reference's partitioned regions become a CachedOp node
(src/operator/subgraph/default_subgraph_property.cc). Here:

* ``_subgraph_exec`` — runs a serialized sub-symbol as its OWN jit
  executable (compiled once per sub-graph, cached); differentiable because
  the jitted pure function is.
* ``_sg_flash_attention`` — the replacement node FlashAttentionProperty
  emits: q/k/v from the matched softmax(QK^T*scale)V chain are fed to the
  Pallas flash kernel.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register

class _SymCache(dict):
    """Parsed-symbol cache (("sym", json) -> Symbol). The jit
    executables themselves live in :mod:`mxtpu.compile_service`;
    ``clear()`` drops those too so a test reset forces real
    recompiles."""

    def clear(self):
        super().clear()
        from .. import compile_service
        compile_service.drop(site="subgraph_exec")


# subgraph_json -> parsed symbol; executables live in the compile service
_SUBGRAPH_CACHE = _SymCache()


def _load_sym(subgraph_json):
    hit = _SUBGRAPH_CACHE.get(("sym", subgraph_json))
    if hit is None:
        from ..symbol.symbol import load_json
        hit = load_json(subgraph_json)
        _SUBGRAPH_CACHE[("sym", subgraph_json)] = hit
    return hit


def _compiled(subgraph_json, input_names, n_outputs):
    import hashlib

    from .. import compile_service as csvc
    from .registry import policy_key
    # policy_key in the cache key: the sub-symbol executes registered ops
    # whose trace-time gates (BN one-pass, conv accumulate, ...) get baked
    # into this executable — a lever flip must recompile, not alias.
    # The compile service is the cache (LRU-bounded — this dict grew
    # without limit under partition-JSON churn). aot=False, never
    # persisted: a partitioned region executes INSIDE an outer executor
    # trace (tracer inputs), which a deserialized AOT executable cannot
    # inline — the OUTER executor entry is what the disk cache persists.
    key = csvc.canonical_key(
        site="subgraph_exec",
        fn_id=hashlib.sha1(
            subgraph_json.encode("utf-8")).hexdigest()[:16],
        signature=(tuple(input_names), int(n_outputs)),
        policy=policy_key(), device=csvc.device_token())
    hit = csvc.get(key)
    if hit is not None:
        return hit.fn
    from ..ndarray import NDArray
    from .. import autograd

    # retrace watchdog: one compile per (sub-graph, policy) — steady-state
    # recompiles here mean partition JSON churn or a mid-run policy flip
    prov = {"inputs": list(input_names), "n_outputs": n_outputs,
            "policy_key": list(key.policy)}

    sym = _load_sym(subgraph_json)
    names = list(input_names)

    def build():
        def pure(*datas):
            prev = autograd.set_recording(False)
            try:
                feed = {n: NDArray(d) for n, d in zip(names, datas)}
                outs = sym._execute(feed)
            finally:
                autograd.set_recording(prev)
            res = [o._data for o in outs]
            return tuple(res) if n_outputs > 1 else res[0]

        return jax.jit(pure)

    return csvc.get_or_build(key, build, provenance=prov, aot=False).fn


@register("_subgraph_exec")
def subgraph_exec(*inputs, subgraph_json=None, input_names=(), n_outputs=1):
    """Execute a partitioned region as its own compiled executable.

    Training mode runs the region INLINE (no private jit): stochastic nodes
    (Dropout) draw fresh keys per call and BatchNorm resolves batch-stats
    mode at call time — a cached private jit would bake one RNG key into the
    executable forever. Inference (the backend-offload use case the
    reference's partitioning serves, e.g. INT8/TRT) gets the cached
    separately-compiled executable. Note: moving-stat (aux) updates of
    BatchNorm nodes hidden inside a partitioned region are not propagated —
    partition for deployment, not for stat-updating training (the
    reference's default property has the same blind spot: aux writes stay
    inside the CachedOp)."""
    from .. import autograd
    from ..ndarray import NDArray

    if autograd.is_training():
        sym = _load_sym(subgraph_json)
        feed = {n: NDArray(d) for n, d in zip(input_names, inputs)}
        outs = sym._execute(feed, is_train=True)
        res = [o._data for o in outs]
        return res if int(n_outputs) > 1 else res[0]
    fn = _compiled(subgraph_json, input_names, int(n_outputs))
    out = fn(*inputs)
    return list(out) if isinstance(out, tuple) else out


@register("_sg_flash_attention")
def sg_flash_attention(q, k, v, scale=1.0, transpose_b=False):
    """Matched attention chain lowered onto the Pallas flash kernel.

    q: [B, T, D]; k: [B, T, D] if the matched batch_dot had transpose_b
    else [B, D, T]; v: [B, T, D]. The matched pattern applied ``scale`` to
    the scores before softmax, so it is forwarded verbatim.
    """
    from .pallas.flash_attention import flash_attention

    if not transpose_b:
        k = jnp.swapaxes(k, 1, 2)
    out = flash_attention(q[:, None], k[:, None], v[:, None], causal=False,
                          scale=float(scale))
    return out[:, 0]
