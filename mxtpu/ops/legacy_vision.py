"""Legacy v1 + SSD vision op stragglers.

Reference: src/operator/crop.cc, src/operator/svm_output.cc,
src/operator/correlation.cc, src/operator/tensor/histogram.cc,
src/operator/contrib/multibox_{prior,target,detection}.cc.

TPU-native notes: Crop/histogram/Correlation lower to pure XLA
(slice/searchsorted/conv-like shifted products — Correlation's static
displacement grid unrolls into fused VPU work, where the reference needed a
dedicated CUDA kernel). SVMOutput mirrors SoftmaxOutput's fused-backward
trick via custom_vjp. The multibox target/detection pair is data-dependent
sequential matching/NMS; on TPU that work belongs on the HOST side of the
input pipeline (the standard TPU SSD recipe), so they run as NumPy under
``jax.pure_callback`` — jit-compatible, non-differentiable by definition
(targets/detections are labels, as in the reference where backward writes
zeros)."""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .registry import register


# ------------------------------------------------------------------- Crop
@register("Crop")
def Crop(*data, offset=(0, 0), h_w=(0, 0), center_crop=False, num_args=None):
    """Crop data (NCHW) to h_w or to crop_like's spatial size
    (ref: src/operator/crop.cc)."""
    x = data[0]
    if len(data) > 1:
        th, tw = data[1].shape[2], data[1].shape[3]
    else:
        th, tw = int(h_w[0]), int(h_w[1])
    if center_crop:
        oy = (x.shape[2] - th) // 2
        ox = (x.shape[3] - tw) // 2
    else:
        oy, ox = int(offset[0]), int(offset[1])
    return jax.lax.dynamic_slice(
        x, (0, 0, oy, ox), (x.shape[0], x.shape[1], th, tw))


# -------------------------------------------------------------- SVMOutput
@register("SVMOutput", aliases=("svm_output",))
def SVMOutput(data, label, margin=1.0, regularization_coefficient=1.0,
              use_linear=False):
    """Identity forward; hinge-loss gradient in backward
    (ref: src/operator/svm_output.cc — like SoftmaxOutput, the loss lives
    in the fused backward kernel)."""

    @jax.custom_vjp
    def _svm(d, lab):
        return d

    def _fwd(d, lab):
        return d, (d, lab)

    def _bwd(res, g):
        d, lab = res
        li = lab.astype(jnp.int32)
        nclass = d.shape[1]
        oh = jax.nn.one_hot(li, nclass, dtype=d.dtype)  # [N, C]
        # score margin per class vs the true-class score
        true_score = jnp.sum(d * oh, axis=1, keepdims=True)
        viol = (margin - (true_score - d)) > 0  # violates the margin
        if use_linear:
            # L1-SVM: +-1 gradients on violating classes
            gneg = jnp.where(viol & (oh == 0), 1.0, 0.0)
        else:
            # L2-SVM: proportional to the violation
            gneg = jnp.where(viol & (oh == 0),
                             2.0 * (margin - (true_score - d)), 0.0)
        gpos = -jnp.sum(gneg, axis=1, keepdims=True) * oh
        grad = (gneg + gpos) * regularization_coefficient
        return (grad.astype(d.dtype), jnp.zeros_like(lab))

    _svm.defvjp(_fwd, _bwd)
    return _svm(data, label)


# -------------------------------------------------------------- histogram
@register("histogram")
def histogram(data, bins=None, bin_cnt=None, range=None):
    """(histo, bin_edges) (ref: src/operator/tensor/histogram.cc). Either
    explicit ``bins`` edges or ``bin_cnt`` + ``range``."""
    x = jnp.ravel(data)
    if bins is not None:
        edges = jnp.asarray(bins)
        cnt = edges.shape[0] - 1
        lo, hi = edges[0], edges[-1]
        idx = jnp.clip(jnp.searchsorted(edges, x, side="right") - 1,
                       0, cnt - 1)
        valid = (x >= lo) & (x <= hi)
    else:
        cnt = int(bin_cnt)
        lo, hi = (jnp.min(x), jnp.max(x)) if range is None else \
            (jnp.float32(range[0]), jnp.float32(range[1]))
        edges = jnp.linspace(lo, hi, cnt + 1)
        width = (hi - lo) / cnt
        idx = jnp.clip(((x - lo) / width).astype(jnp.int32), 0, cnt - 1)
        valid = (x >= lo) & (x <= hi)
    counts = jnp.zeros((cnt,), jnp.int64 if jax.config.x64_enabled
                       else jnp.int32)
    counts = counts.at[idx].add(valid.astype(counts.dtype))
    return [counts, edges]


# ------------------------------------------------------------ Correlation
@register("Correlation")
def Correlation(data1, data2, kernel_size=1, max_displacement=1, stride1=1,
                stride2=1, pad_size=0, is_multiply=True):
    """FlowNet correlation layer (ref: src/operator/correlation.cc).

    The displacement grid is static, so it unrolls into shifted elementwise
    products + average pooling — all XLA-fusible; the reference needed a
    bespoke CUDA kernel (correlation.cu)."""
    n, c, h, w = data1.shape
    k = int(kernel_size)
    bd = int(max_displacement)
    s1, s2 = int(stride1), int(stride2)
    pad = int(pad_size)
    kr = k // 2
    border = bd + kr
    p1 = jnp.pad(data1, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    p2 = jnp.pad(data2, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    ph, pw = h + 2 * pad, w + 2 * pad
    out_h = int(np.ceil((ph - border * 2) / float(s1)))
    out_w = int(np.ceil((pw - border * 2) / float(s1)))
    grid = int(np.floor(2.0 * bd / s2) + 1)
    ys = border + s1 * jnp.arange(out_h)
    xs = border + s1 * jnp.arange(out_w)
    planes = []
    for dy in (-bd + s2 * np.arange(grid)):
        for dx in (-bd + s2 * np.arange(grid)):
            acc = 0.0
            for ky in np.arange(-kr, kr + 1):
                for kx in np.arange(-kr, kr + 1):
                    a = p1[:, :, ys + ky][:, :, :, xs + kx]
                    b = p2[:, :, ys + ky + int(dy)][:, :, :,
                                                    xs + kx + int(dx)]
                    acc = acc + (a * b if is_multiply else
                                 jnp.abs(a - b))
            planes.append(jnp.sum(acc, axis=1) / (k * k * c))
    return jnp.stack(planes, axis=1)


# ---------------------------------------------------------- multibox SSD
@register("_contrib_MultiBoxPrior", aliases=("multibox_prior",))
def MultiBoxPrior(data, sizes=(1.0,), ratios=(1.0,), clip=False,
                  steps=(-1.0, -1.0), offsets=(0.5, 0.5)):
    """SSD prior boxes (ref: multibox_prior-inl.h MultiBoxPriorForward);
    fully static — computed as one fused XLA expression."""
    in_h, in_w = data.shape[2], data.shape[3]
    step_y = 1.0 / in_h if steps[0] <= 0 else float(steps[0])
    step_x = 1.0 / in_w if steps[1] <= 0 else float(steps[1])
    cy = (jnp.arange(in_h) + float(offsets[0])) * step_y  # [H]
    cx = (jnp.arange(in_w) + float(offsets[1])) * step_x  # [W]
    hw = []
    for s in sizes:  # ratio 1, all sizes
        hw.append((float(s) * in_h / in_w / 2.0, float(s) / 2.0))
    for r in ratios[1:]:  # size[0], remaining ratios
        sr = float(np.sqrt(r))
        hw.append((float(sizes[0]) * in_h / in_w * sr / 2.0,
                   float(sizes[0]) / sr / 2.0))
    half_w = jnp.asarray([p[0] for p in hw])  # [A]
    half_h = jnp.asarray([p[1] for p in hw])
    shape = (in_h, in_w, half_w.shape[0])
    CY = jnp.broadcast_to(cy[:, None, None], shape)
    CX = jnp.broadcast_to(cx[None, :, None], shape)
    HW = jnp.broadcast_to(half_w[None, None, :], shape)
    HH = jnp.broadcast_to(half_h[None, None, :], shape)
    boxes = jnp.stack([CX - HW, CY - HH, CX + HW, CY + HH],
                      axis=-1)  # [H, W, A, 4]
    out = boxes.reshape(1, -1, 4)
    if clip:
        out = jnp.clip(out, 0.0, 1.0)
    return out


def _np_multibox_target(anchors, labels, cls_preds, overlap_threshold,
                        ignore_label, negative_mining_ratio,
                        negative_mining_thresh, minimum_negative_samples,
                        variances):
    """NumPy matching (ref: multibox_target.cc MultiBoxTargetForward):
    greedy bipartite match, threshold match, optional hard-negative mining,
    variance-encoded location targets."""
    anchors = anchors.reshape(-1, 4)
    num_anchors = anchors.shape[0]
    nb = labels.shape[0]
    loc_target = np.zeros((nb, num_anchors * 4), np.float32)
    loc_mask = np.zeros((nb, num_anchors * 4), np.float32)
    cls_target = np.full((nb, num_anchors), ignore_label, np.float32)

    def iou(a, b):
        ix = max(0.0, min(a[2], b[2]) - max(a[0], b[0]))
        iy = max(0.0, min(a[3], b[3]) - max(a[1], b[1]))
        inter = ix * iy
        ua = (a[2] - a[0]) * (a[3] - a[1]) + (b[2] - b[0]) * (b[3] - b[1]) \
            - inter
        return inter / ua if ua > 0 else 0.0

    for b in range(nb):
        lab = labels[b]
        valid = []
        for row in lab:
            if row[0] == -1.0:
                break
            valid.append(row)
        cls_target[b] = 0.0  # default background
        if not valid:
            continue
        ov = np.array([[iou(anchors[j], g[1:5]) for g in valid]
                       for j in range(num_anchors)], np.float32)
        matched_gt = np.full(num_anchors, -1, np.int64)
        anchor_used = np.zeros(num_anchors, bool)
        gt_used = np.zeros(len(valid), bool)
        # greedy bipartite: each gt grabs its best remaining anchor
        while not gt_used.all():
            masked = ov.copy()
            masked[anchor_used] = -1.0
            masked[:, gt_used] = -1.0
            j, k = np.unravel_index(np.argmax(masked), masked.shape)
            if masked[j, k] <= 1e-6:
                break
            matched_gt[j] = k
            anchor_used[j] = True
            gt_used[k] = True
        if overlap_threshold > 0:
            for j in range(num_anchors):
                if anchor_used[j]:
                    continue
                k = int(np.argmax(ov[j]))
                if ov[j, k] > overlap_threshold:
                    matched_gt[j] = k
                    anchor_used[j] = True
        # negative mining
        if negative_mining_ratio > 0:
            num_pos = int(anchor_used.sum())
            num_neg = min(int(num_pos * negative_mining_ratio),
                          num_anchors - num_pos)
            num_neg = max(num_neg, int(minimum_negative_samples))
            # hardness = -softmax_prob(background), exactly the reference's
            # ranking (multibox_target.cc:218-232): a confidently-wrong
            # anchor (low bg prob) is the hardest negative
            p = cls_preds[b]  # [C, A]
            e = np.exp(p - p.max(axis=0, keepdims=True))
            bg_prob = e[0] / e.sum(axis=0)
            scores = -bg_prob  # higher = harder negative
            cand = [(scores[j], j) for j in range(num_anchors)
                    if not anchor_used[j] and ov[j].max()
                    < negative_mining_thresh]
            cand.sort(key=lambda t: -t[0])
            keep_neg = {j for _, j in cand[:num_neg]}
            for j in range(num_anchors):
                if not anchor_used[j] and j not in keep_neg:
                    cls_target[b, j] = ignore_label
        for j in range(num_anchors):
            k = matched_gt[j]
            if k < 0:
                continue
            g = valid[k]
            cls_target[b, j] = g[0] + 1  # class id + 1 (0 = background)
            ax = (anchors[j, 0] + anchors[j, 2]) / 2
            ay = (anchors[j, 1] + anchors[j, 3]) / 2
            aw = anchors[j, 2] - anchors[j, 0]
            ah = anchors[j, 3] - anchors[j, 1]
            gx = (g[1] + g[3]) / 2
            gy = (g[2] + g[4]) / 2
            gw = g[3] - g[1]
            gh = g[4] - g[2]
            loc_target[b, j * 4:(j + 1) * 4] = [
                (gx - ax) / aw / variances[0],
                (gy - ay) / ah / variances[1],
                float(np.log(max(gw / aw, 1e-12))) / variances[2],
                float(np.log(max(gh / ah, 1e-12))) / variances[3]]
            loc_mask[b, j * 4:(j + 1) * 4] = 1.0
    return loc_target, loc_mask, cls_target


@register("_contrib_MultiBoxTarget", aliases=("multibox_target",))
def MultiBoxTarget(anchor, label, cls_pred, overlap_threshold=0.5,
                   ignore_label=-1.0, negative_mining_ratio=-1.0,
                   negative_mining_thresh=0.5, minimum_negative_samples=0,
                   variances=(0.1, 0.1, 0.2, 0.2)):
    """SSD training targets (ref: multibox_target.cc). Host-side matching
    via pure_callback (see module docstring): [loc_target, loc_mask,
    cls_target]."""
    num_anchors = anchor.shape[1]
    nb = label.shape[0]
    fn = functools.partial(
        _np_multibox_target, overlap_threshold=float(overlap_threshold),
        ignore_label=float(ignore_label),
        negative_mining_ratio=float(negative_mining_ratio),
        negative_mining_thresh=float(negative_mining_thresh),
        minimum_negative_samples=int(minimum_negative_samples),
        variances=tuple(float(v) for v in variances))
    out_shapes = (
        jax.ShapeDtypeStruct((nb, num_anchors * 4), jnp.float32),
        jax.ShapeDtypeStruct((nb, num_anchors * 4), jnp.float32),
        jax.ShapeDtypeStruct((nb, num_anchors), jnp.float32))
    lt, lm, ct = jax.pure_callback(
        lambda a, l, c: fn(np.asarray(a, np.float32),
                           np.asarray(l, np.float32),
                           np.asarray(c, np.float32)),
        out_shapes, anchor, label, cls_pred)
    return [lt, lm, ct]


def _np_multibox_detection(cls_prob, loc_pred, anchors, threshold, clip,
                           background_id, nms_threshold, force_suppress,
                           variances, nms_topk, keep_topk):
    """NumPy decode + per-class NMS (ref: multibox_detection.cc)."""
    anchors = anchors.reshape(-1, 4)
    nb, num_classes, num_anchors = cls_prob.shape
    out = np.full((nb, num_anchors, 6), -1.0, np.float32)
    for b in range(nb):
        dets = []
        for j in range(num_anchors):
            cid = int(np.argmax(cls_prob[b, :, j]))
            score = float(cls_prob[b, cid, j])
            if cid == background_id or score < threshold:
                continue
            ax = (anchors[j, 0] + anchors[j, 2]) / 2
            ay = (anchors[j, 1] + anchors[j, 3]) / 2
            aw = anchors[j, 2] - anchors[j, 0]
            ah = anchors[j, 3] - anchors[j, 1]
            p = loc_pred[b, j * 4:(j + 1) * 4]
            cx = p[0] * variances[0] * aw + ax
            cy = p[1] * variances[1] * ah + ay
            w = float(np.exp(p[2] * variances[2])) * aw / 2
            h = float(np.exp(p[3] * variances[3])) * ah / 2
            box = [cx - w, cy - h, cx + w, cy + h]
            if clip:
                box = [min(max(v, 0.0), 1.0) for v in box]
            # class id shifted down by one when background is class 0
            oid = cid - 1 if background_id == 0 else cid
            dets.append([float(oid), score] + box)
        dets.sort(key=lambda d: -d[1])
        if nms_topk > 0:
            dets = dets[:nms_topk]
        keep = []  # truncated to keep_topk after NMS (below)
        for d in dets:
            ok = True
            for kd in keep:
                if not force_suppress and kd[0] != d[0]:
                    continue
                ix = max(0.0, min(d[4], kd[4]) - max(d[2], kd[2]))
                iy = max(0.0, min(d[5], kd[5]) - max(d[3], kd[3]))
                inter = ix * iy
                ua = (d[4] - d[2]) * (d[5] - d[3]) \
                    + (kd[4] - kd[2]) * (kd[5] - kd[3]) - inter
                if ua > 0 and inter / ua > nms_threshold:
                    ok = False
                    break
            if ok:
                keep.append(d)
        if keep_topk > 0:
            keep = keep[:keep_topk]
        for i, d in enumerate(keep):
            out[b, i] = d
    return out


@register("_contrib_MultiBoxDetection", aliases=("multibox_detection",))
def MultiBoxDetection(cls_prob, loc_pred, anchor, clip=True, threshold=0.01,
                      background_id=0, nms_threshold=0.5,
                      force_suppress=False, variances=(0.1, 0.1, 0.2, 0.2),
                      nms_topk=-1, keep_topk=-1):
    """SSD detection decode + NMS (ref: multibox_detection.cc). Host-side
    via pure_callback; output [N, num_anchors, 6] rows of
    (class_id, score, xmin, ymin, xmax, ymax), -1-padded."""
    nb = cls_prob.shape[0]
    num_anchors = anchor.shape[1]
    fn = functools.partial(
        _np_multibox_detection, threshold=float(threshold), clip=bool(clip),
        background_id=int(background_id),
        nms_threshold=float(nms_threshold),
        force_suppress=bool(force_suppress),
        variances=tuple(float(v) for v in variances),
        nms_topk=int(nms_topk), keep_topk=int(keep_topk))
    out = jax.pure_callback(
        lambda c, l, a: fn(np.asarray(c, np.float32),
                           np.asarray(l, np.float32),
                           np.asarray(a, np.float32)),
        jax.ShapeDtypeStruct((nb, num_anchors, 6), jnp.float32),
        cls_prob, loc_pred, anchor)
    return out


# ------------------------------------------------------------- v1 aliases
@register("BatchNorm_v1", aliases=("batch_norm_v1",), wrap=False)
def BatchNorm_v1(data, gamma, beta, moving_mean, moving_var, eps=1e-3,
                 momentum=0.9, fix_gamma=True, use_global_stats=False,
                 output_mean_var=False):
    """Legacy BatchNorm (ref: src/operator/batch_norm_v1.cc). The v1 op is
    the modern one restricted to axis=1 and without cudnn_off — delegated;
    kept as a distinct registry name so old symbol JSON deserializes."""
    from .nn import BatchNorm
    return BatchNorm(data, gamma, beta, moving_mean, moving_var, eps=eps,
                     momentum=momentum, fix_gamma=fix_gamma,
                     use_global_stats=use_global_stats,
                     output_mean_var=output_mean_var, axis=1)


@register("Convolution_v1", aliases=("convolution_v1",))
def Convolution_v1(data, weight, bias=None, kernel=None, stride=None,
                   dilate=None, pad=None, num_filter=None, num_group=1,
                   no_bias=False, workspace=None, cudnn_tune=None,
                   cudnn_off=None):
    """Legacy Convolution (ref: src/operator/convolution_v1.cc) — same math
    as the modern op in NCHW; kept for old symbol JSON."""
    from .registry import get_op
    Convolution = get_op("Convolution").fn  # unwrapped: jnp in, jnp out
    return Convolution(data, weight, bias, kernel=kernel, stride=stride,
                       dilate=dilate, pad=pad, num_filter=num_filter,
                       num_group=num_group, no_bias=no_bias)


@register("Pooling_v1", aliases=("pooling_v1",))
def Pooling_v1(data, kernel=None, pool_type="max", global_pool=False,
               stride=None, pad=None, pooling_convention="valid"):
    """Legacy Pooling (ref: src/operator/pooling_v1.cc)."""
    from .registry import get_op
    Pooling = get_op("Pooling").fn  # unwrapped: jnp in, jnp out
    return Pooling(data, kernel=kernel, pool_type=pool_type,
                   global_pool=global_pool, stride=stride, pad=pad,
                   pooling_convention=pooling_convention)


@register("IdentityAttachKLSparseReg", wrap=False)
def IdentityAttachKLSparseReg(data, sparseness_target=0.1, penalty=0.001,
                              momentum=0.9):
    """Identity forward; backward adds the KL sparseness-regularization
    gradient on sigmoid activations (ref:
    src/operator/identity_attach_KL_sparse_reg.cc): for unit-mean rho_hat,
    d += penalty * (-rho/rho_hat + (1-rho)/(1-rho_hat)).

    Deviation from the reference: rho_hat is the CURRENT batch mean, not a
    momentum moving average across batches (the reference keeps moving
    rho_hat as mutable op state; this op is pure). ``momentum`` is
    therefore ignored — warned once below — and with small batches the
    regularization gradient is noisier than the reference's."""
    import logging

    import jax
    from ..ndarray.ndarray import _apply

    if momentum != 0.9 and not getattr(IdentityAttachKLSparseReg,
                                       "_warned", False):
        IdentityAttachKLSparseReg._warned = True
        logging.getLogger(__name__).warning(
            "IdentityAttachKLSparseReg: momentum is ignored — rho_hat is "
            "the current batch mean (pure-op deviation from the reference)")
    rho = sparseness_target

    def fn(x):
        @jax.custom_vjp
        def ident(x):
            return x

        def fwd(x):
            # rho_hat: batch mean activation per hidden unit (axis 0)
            return x, jnp.clip(jnp.mean(x, axis=0), 1e-6, 1 - 1e-6)

        def bwd(rho_hat, g):
            reg = penalty * (-rho / rho_hat + (1 - rho) / (1 - rho_hat))
            return (g + jnp.broadcast_to(reg, g.shape).astype(g.dtype),)

        ident.defvjp(fwd, bwd)
        return ident(x)

    return _apply(fn, (data,), name="IdentityAttachKLSparseReg")
