"""Image ops: decode-side tensor transforms.

Reference: ``src/operator/image/image_random-inl.h`` (to_tensor, normalize,
random flips/brightness/contrast/saturation/hue/lighting) and ``mx.image``
resize/crop kernels (python/mxnet/image/image.py over OpenCV).

TPU-native notes: everything is pure jnp so transforms fuse into the input
pipeline under jit; resize lowers to ``jax.image.resize`` (XLA gather/matmul)
instead of OpenCV. Layout convention follows the reference: HWC uint8/float
in, ``to_tensor`` produces CHW float32.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register
from .. import random as _random

__all__ = ["image_to_tensor", "image_normalize", "image_resize",
           "image_crop", "image_center_crop", "image_flip_left_right",
           "image_flip_top_bottom", "image_random_flip_left_right",
           "image_random_flip_top_bottom", "image_brightness",
           "image_contrast", "image_saturation", "image_hue"]

_LUMA = (0.299, 0.587, 0.114)


@register("_image_to_tensor", aliases=("image_to_tensor",))
def image_to_tensor(data):
    """HWC [0,255] -> CHW [0,1] float32 (ref: image_random-inl.h ToTensor).
    Batched NHWC input becomes NCHW."""
    x = data.astype(jnp.float32) / 255.0
    if x.ndim == 3:
        return jnp.transpose(x, (2, 0, 1))
    return jnp.transpose(x, (0, 3, 1, 2))


@register("_image_normalize", aliases=("image_normalize",))
def image_normalize(data, mean=0.0, std=1.0):
    """Channel-wise (x - mean) / std on CHW input (ref: Normalize)."""
    mean = jnp.asarray(mean, jnp.float32)
    std = jnp.asarray(std, jnp.float32)
    if data.ndim == 3:   # CHW
        mean = mean.reshape((-1, 1, 1)) if mean.ndim else mean
        std = std.reshape((-1, 1, 1)) if std.ndim else std
    else:                # NCHW
        mean = mean.reshape((1, -1, 1, 1)) if mean.ndim else mean
        std = std.reshape((1, -1, 1, 1)) if std.ndim else std
    return (data.astype(jnp.float32) - mean) / std


@register("_image_resize", aliases=("image_resize",))
def image_resize(data, size=None, keep_ratio=False, interp=1):
    """Resize HWC (or NHWC) images (ref: mx.image.imresize). interp: 0=nearest,
    1=bilinear, 2=bicubic (maps to jax.image methods)."""
    method = {0: "nearest", 1: "linear", 2: "cubic"}.get(int(interp), "linear")
    if isinstance(size, int):
        size = (size, size)
    w, h = size  # reference convention: size=(w, h)
    if data.ndim == 3:
        out_shape = (h, w, data.shape[2])
    else:
        out_shape = (data.shape[0], h, w, data.shape[3])
    out = jax.image.resize(data.astype(jnp.float32), out_shape, method=method)
    return out.astype(data.dtype) if jnp.issubdtype(data.dtype, jnp.integer) \
        else out


@register("_image_crop", aliases=("image_crop",))
def image_crop(data, x=0, y=0, width=None, height=None):
    """Fixed crop of HWC/NHWC (ref: mx.image.fixed_crop)."""
    if data.ndim == 3:
        return data[y:y + height, x:x + width, :]
    return data[:, y:y + height, x:x + width, :]


@register("_image_center_crop", aliases=("image_center_crop",))
def image_center_crop(data, size=None):
    if isinstance(size, int):
        size = (size, size)
    w, h = size
    H, W = (data.shape[0], data.shape[1]) if data.ndim == 3 \
        else (data.shape[1], data.shape[2])
    y = max((H - h) // 2, 0)
    x = max((W - w) // 2, 0)
    return _crop_raw(data, x, y, w, h)


def _crop_raw(data, x, y, w, h):
    if data.ndim == 3:
        return data[y:y + h, x:x + w, :]
    return data[:, y:y + h, x:x + w, :]


@register("_image_flip_left_right", aliases=("image_flip_left_right",))
def image_flip_left_right(data):
    axis = 1 if data.ndim == 3 else 2
    return jnp.flip(data, axis=axis)


@register("_image_flip_top_bottom", aliases=("image_flip_top_bottom",))
def image_flip_top_bottom(data):
    axis = 0 if data.ndim == 3 else 1
    return jnp.flip(data, axis=axis)


@register("_image_random_flip_left_right",
          aliases=("image_random_flip_left_right",))
def image_random_flip_left_right(data, p=0.5):
    key = _random.next_key()
    flip = jax.random.bernoulli(key, p)
    axis = 1 if data.ndim == 3 else 2
    return jnp.where(flip, jnp.flip(data, axis=axis), data)


@register("_image_random_flip_top_bottom",
          aliases=("image_random_flip_top_bottom",))
def image_random_flip_top_bottom(data, p=0.5):
    key = _random.next_key()
    flip = jax.random.bernoulli(key, p)
    axis = 0 if data.ndim == 3 else 1
    return jnp.where(flip, jnp.flip(data, axis=axis), data)


def _blend(a, b, alpha):
    return a.astype(jnp.float32) * alpha + b * (1.0 - alpha)


@register("_image_brightness", aliases=("image_brightness",))
def image_brightness(data, alpha=1.0):
    return _blend(data, 0.0, alpha).astype(jnp.float32)


@register("_image_contrast", aliases=("image_contrast",))
def image_contrast(data, alpha=1.0):
    coef = jnp.asarray(_LUMA, jnp.float32)
    c_axis = -1  # HWC / NHWC
    gray = jnp.sum(data.astype(jnp.float32) * coef, axis=c_axis, keepdims=True)
    mean = jnp.mean(gray, axis=(-3, -2), keepdims=True)
    return _blend(data, mean, alpha)


@register("_image_saturation", aliases=("image_saturation",))
def image_saturation(data, alpha=1.0):
    coef = jnp.asarray(_LUMA, jnp.float32)
    gray = jnp.sum(data.astype(jnp.float32) * coef, axis=-1, keepdims=True)
    return _blend(data, gray, alpha)


@register("_image_hue", aliases=("image_hue",))
def image_hue(data, alpha=0.0):
    """Approximate hue rotation via the YIQ rotation matrix
    (ref: image_random-inl.h RandomHue's yiq transform)."""
    u = jnp.cos(alpha * jnp.pi)
    w = jnp.sin(alpha * jnp.pi)
    t_yiq = jnp.asarray([[0.299, 0.587, 0.114],
                         [0.596, -0.274, -0.321],
                         [0.211, -0.523, 0.311]], jnp.float32)
    t_rgb = jnp.asarray([[1.0, 0.956, 0.621],
                         [1.0, -0.272, -0.647],
                         [1.0, -1.107, 1.705]], jnp.float32)
    rot = jnp.asarray([[1.0, 0.0, 0.0],
                       [0.0, u, -w],
                       [0.0, w, u]], jnp.float32)
    m = t_rgb @ rot @ t_yiq
    return jnp.einsum("...c,dc->...d", data.astype(jnp.float32), m)
