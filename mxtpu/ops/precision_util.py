"""MXU precision selection for contraction ops.

The package-global ``jax_default_matmul_precision='float32'``
(mxtpu/__init__.py) exists to keep FLOAT32 contractions honest: without
it, XLA:TPU silently truncates f32 operands to one-pass bf16. But that
global also tags BF16 contractions HIGHEST, which makes XLA run them
through the multi-pass f32-emulation path — 3-6x slower on the MXU for
zero numerical benefit (one-pass bf16x bf16 with f32 accumulation is
already exact for bf16 operands). This was the round-1/round-2 ResNet-50
throughput ceiling: every conv in the train step lowered with
``precision HIGHEST`` (see PERF.md).

``mxu_precision(*operands)`` returns the right per-op override:
DEFAULT when every floating operand is sub-f32 (bf16/f16), None (inherit
the honest global) otherwise. Same policy as the flash-attention kernel
(mxtpu/ops/pallas/flash_attention.py:71-75), applied everywhere a
contraction is issued.
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax

_LOW = (jnp.bfloat16, jnp.float16)


def mxu_precision(*operands):
    """Precision override for lax dot/conv given the actual operands."""
    dtypes = [o.dtype for o in operands if hasattr(o, "dtype")]
    if dtypes and all(d in _LOW for d in dtypes):
        return lax.Precision.DEFAULT
    return None
