"""MXU precision selection for contraction ops.

The package-global ``jax_default_matmul_precision='float32'``
(mxtpu/__init__.py) exists to keep FLOAT32 contractions honest: without
it, XLA:TPU silently truncates f32 operands to one-pass bf16. But that
global also tags BF16 contractions HIGHEST, which makes XLA run them
through the multi-pass f32-emulation path — 3-6x slower on the MXU for
zero numerical benefit (one-pass bf16x bf16 with f32 accumulation is
already exact for bf16 operands). This was the round-1/round-2 ResNet-50
throughput ceiling: every conv in the train step lowered with
``precision HIGHEST`` (see PERF.md).

``mxu_precision(*operands)`` returns the right per-op override:
DEFAULT when every floating operand is sub-f32 (bf16/f16), None (inherit
the honest global) otherwise. Same policy as the flash-attention kernel
(mxtpu/ops/pallas/flash_attention.py:71-75), applied everywhere a
contraction is issued.
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax

_LOW = (jnp.bfloat16, jnp.float16)


def mxu_precision(*operands):
    """Precision override for lax dot/conv given the actual operands."""
    dtypes = [o.dtype for o in operands if hasattr(o, "dtype")]
    if dtypes and all(d in _LOW for d in dtypes):
        return lax.Precision.DEFAULT
    return None


def acc_dtype(*operands):
    """preferred_element_type for a contraction over these operands.

    For all-bf16/f16 operands, requesting an f32 accumulator output makes
    XLA:TPU pick a measurably faster MXU schedule than the bf16-out form —
    tools/perf_peak.py measures 102 -> 140 TFLOP/s on an 8k x 8k matmul
    (the cast back to bf16 fuses into the epilogue and keeps the gain).
    Numerics only improve: the accumulator was f32 either way; this keeps
    it f32 through the epilogue instead of rounding per-tile.

    Returns jnp.float32 for low-precision operands, else None. jax 0.9
    supports preferred_element_type under autodiff for dot_general but NOT
    for conv_general_dilated (its transpose rule rejects the mixed-dtype
    cotangent) — conv uses the custom-vjp wrapper in conv_acc.py instead.
    """
    dtypes = [o.dtype for o in operands if hasattr(o, "dtype")]
    if dtypes and all(d in _LOW for d in dtypes):
        return jnp.float32
    return None


def acc_out_dtype(*operands):
    """Output dtype after the f32-accumulate round trip: the operands'
    PROMOTED dtype (bf16 x bf16 -> bf16, but bf16 x f16 -> f32 exactly as
    jnp promotion produced before the fast path existed — casting to the
    first operand's dtype would silently change the public op's dtype and
    make it argument-order dependent)."""
    return jnp.result_type(*operands)


def dot_acc(x, w, dimension_numbers):
    """lax.dot_general with the fast-accumulate policy applied: f32
    accumulator for low-precision operands, result cast back to the
    operands' promoted dtype; full-precision operands inherit the honest-f32
    global."""
    pet = acc_dtype(x, w)
    y = lax.dot_general(x, w, dimension_numbers,
                        precision=mxu_precision(x, w),
                        preferred_element_type=pet)
    return y.astype(acc_out_dtype(x, w)) if pet is not None else y
