"""MXU precision + accumulation policy for contraction ops.

The package-global ``jax_default_matmul_precision='float32'``
(mxtpu/__init__.py) exists to keep FLOAT32 contractions honest: without
it, XLA:TPU silently truncates f32 operands to one-pass bf16. That global
also tags BF16 contractions HIGHEST; ``mxu_precision(*operands)`` overrides
to DEFAULT when every floating operand is sub-f32 (bf16/f16) and returns
None (inherit the honest global) otherwise — the correct policy, though
measurement showed bf16-at-HIGHEST was NOT the historical throughput
ceiling (83 vs 85 TFLOP/s; an earlier 3-6x claim was a sync artifact —
see PERF.md "RETRACTED").

What DOES move the MXU (PERF.md "achievable ceiling"): asking low-precision
contractions for an **f32 accumulator output** (``preferred_element_type``)
— 102 -> 140 TFLOP/s on an 8k matmul, +10% on conv stacks — implemented by
``acc_dtype``/``dot_acc`` here and the conv custom-vjp in conv_acc.py.
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax

_LOW = (jnp.bfloat16, jnp.float16)


def mxu_precision(*operands):
    """Precision override for lax dot/conv given the actual operands."""
    dtypes = [o.dtype for o in operands if hasattr(o, "dtype")]
    if dtypes and all(d in _LOW for d in dtypes):
        return lax.Precision.DEFAULT
    return None


def acc_dtype(*operands):
    """preferred_element_type for a contraction over these operands.

    For all-bf16/f16 operands, requesting an f32 accumulator output makes
    XLA:TPU pick a measurably faster MXU schedule than the bf16-out form —
    tools/perf_peak.py measures 102 -> 140 TFLOP/s on an 8k x 8k matmul
    (the cast back to bf16 fuses into the epilogue and keeps the gain).
    Numerics only improve: the accumulator was f32 either way; this keeps
    it f32 through the epilogue instead of rounding per-tile.

    Returns jnp.float32 for low-precision operands, else None. jax 0.9
    supports preferred_element_type under autodiff for dot_general but NOT
    for conv_general_dilated (its transpose rule rejects the mixed-dtype
    cotangent) — conv uses the custom-vjp wrapper in conv_acc.py instead.
    """
    dtypes = [o.dtype for o in operands if hasattr(o, "dtype")]
    if dtypes and all(d in _LOW for d in dtypes):
        return jnp.float32
    return None


def acc_out_dtype(*operands):
    """Output dtype after the f32-accumulate round trip: the operands'
    PROMOTED dtype (bf16 x bf16 -> bf16, but bf16 x f16 -> f32 exactly as
    jnp promotion produced before the fast path existed — casting to the
    first operand's dtype would silently change the public op's dtype and
    make it argument-order dependent)."""
    return jnp.result_type(*operands)


def contract_acc(contraction, a, b, **kwargs):
    """ONE copy of the fast-accumulate policy for any jnp/lax contraction
    callable taking (a, b, ..., precision=, preferred_element_type=): f32
    accumulator for low-precision operands with the result cast back to the
    operands' promoted dtype; full-precision operands inherit the honest-f32
    global. Used by FullyConnected, dot, batch_dot and the RNN gate matmuls
    so the policy cannot drift between call sites (convs need the
    custom-vjp variant in conv_acc.py instead)."""
    pet = acc_dtype(a, b)
    out = contraction(a, b, precision=mxu_precision(a, b),
                      preferred_element_type=pet, **kwargs)
    return out.astype(acc_out_dtype(a, b)) if pet is not None else out


def dot_acc(x, w, dimension_numbers):
    """lax.dot_general under the fast-accumulate policy (contract_acc)."""
    return contract_acc(lax.dot_general, x, w,
                        dimension_numbers=dimension_numbers)
