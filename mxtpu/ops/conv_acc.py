"""bf16 convolution with an f32 MXU accumulator, fwd AND bwd.

Why: on v5e, XLA picks a measurably faster MXU schedule when a bf16
contraction is asked to produce an f32 accumulator output (the cast back
to bf16 fuses into the epilogue and keeps the gain) — tools/perf_peak.py
measures 102 -> 140 TFLOP/s on a square matmul and tools/perf_conv_acc.py
+10%% on a resnet-like 3x3 conv stack. Numerics only improve: the
per-tile accumulator was f32 either way.

Why a custom_vjp: jax 0.9 supports ``preferred_element_type`` under
autodiff for ``dot_general`` but NOT for ``conv_general_dilated`` — its
transpose rule calls the grad convs with the (now f32) cotangent against
the bf16 saved operand and rejects the dtype mix. Here the primal output
is cast back to bf16, so the cotangent arrives in bf16 and the two grad
convolutions run with matched bf16 operands + their own f32 accumulator:
every conv in fwd and bwd is on the fast path.

The grad convs reuse jax's own transpose-rule implementations
(jax._src.lax.convolution._conv_general_dilated_transpose_{lhs,rhs}) so
the stride/dilation/grouping padding arithmetic cannot drift from what
``jax.grad`` of a plain conv would compute. That import is private and
version-brittle: it is probed once at import; when unavailable,
``HAVE_ACC_VJP`` is False and callers (ops/nn.py Convolution) fall back
to the plain autodiff path — a perf regression, never a correctness one.
tests/test_precision.py asserts grads match the plain path.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

try:  # private jax internals — probed once, fallback below
    from jax._src.lax.convolution import (
        _conv_general_dilated_transpose_lhs as _t_lhs,
        _conv_general_dilated_transpose_rhs as _t_rhs,
    )
    HAVE_ACC_VJP = True
except ImportError:  # pragma: no cover - exercised only on a jax upgrade
    _t_lhs = _t_rhs = None
    HAVE_ACC_VJP = False

_LOW = (jnp.bfloat16, jnp.float16)


def _conv_raw(x, w, strides, padding, lhs_dilation, rhs_dilation, dims,
              groups, pet):
    out = lax.conv_general_dilated(
        x, w,
        window_strides=strides,
        padding=padding,
        lhs_dilation=lhs_dilation,
        rhs_dilation=rhs_dilation,
        dimension_numbers=dims,
        feature_group_count=groups,
        precision=lax.Precision.DEFAULT,
        preferred_element_type=pet,
    )
    return out.astype(x.dtype)


@partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5, 6, 7))
def conv_acc(x, w, strides, padding, lhs_dilation, rhs_dilation, dims,
             groups):
    """bf16/f16 conv, f32-accumulated fwd and bwd, output in x.dtype.

    ``dims`` is the (lhs, rhs, out) string triple; ``padding`` a tuple of
    per-dim (lo, hi) pairs. Callers guarantee all-low-precision operands
    (ops/nn.py routes here only when acc_dtype(...) fires).
    """
    return _conv_raw(x, w, strides, padding, lhs_dilation, rhs_dilation,
                     dims, groups, jnp.float32)


def _fwd(x, w, strides, padding, lhs_dilation, rhs_dilation, dims, groups):
    out = conv_acc(x, w, strides, padding, lhs_dilation, rhs_dilation, dims,
                   groups)
    return out, (x, w)


def _bwd(strides, padding, lhs_dilation, rhs_dilation, dims, groups, res, g):
    x, w = res
    dn = lax.conv_dimension_numbers(x.shape, w.shape, dims)
    kw = dict(window_strides=strides, padding=padding,
              lhs_dilation=lhs_dilation, rhs_dilation=rhs_dilation,
              dimension_numbers=dn, feature_group_count=groups,
              batch_group_count=1, precision=lax.Precision.DEFAULT,
              preferred_element_type=jnp.float32)
    try:
        gx = _t_lhs(g, x, w, out_sharding=None, **kw)
        gw = _t_rhs(g, x, w, out_sharding=None, **kw)
    except TypeError:  # out_sharding kwarg is newer than some jax versions
        gx = _t_lhs(g, x, w, **kw)
        gw = _t_rhs(g, x, w, **kw)
    return gx.astype(x.dtype), gw.astype(w.dtype)


conv_acc.defvjp(_fwd, _bwd)


def _enabled():
    """DEFAULT OFF as of round 5: the same-session on-chip A/B measured
    the custom conv path at −2.8% end-to-end ResNet-50 (2331.7 control
    vs 2267.2, perf_watch.log 16:16) and the best-known config excludes
    it (resnet_best 2580.3 img/s, perf_followup.log) — the +10%
    conv-stack microbench win does not survive the real mixed graph.
    MXTPU_CONV_ACC=1 re-enables for A/Bs. The f32-accumulate MATMUL
    policy (precision_util.contract_acc: dense/RNN/attention) is
    unaffected by this flag and stays on."""
    import os
    return os.environ.get("MXTPU_CONV_ACC", "0") == "1"


def _im2col_enabled():
    """MXTPU_CONV_IM2COL=1 lowers qualifying convs (NHWC, stride 1, no
    dilation, groups 1, C_in <= 128) through explicit patch extraction +
    ONE matmul instead of XLA's conv path. Why (round-5 measurement,
    PERF.md): the early resnet stages' small-channel convs run at ~7
    TFLOP/s on the conv path while the same chip's MATMUL path measures
    102-135 TFLOP/s — im2col trades ~k^2 x input HBM traffic (~1 ms at
    these shapes) for matmul-path compute. STAGED off by default pending
    the on-chip A/B (the auto-battery's resnet_im2col phase); in the jit
    policy cache key (registry.policy_key)."""
    import os
    return os.environ.get("MXTPU_CONV_IM2COL", "0") == "1"


def _im2col_applicable(x, w, strides, padding, lhs_dilation, rhs_dilation,
                       dims, groups):
    if dims != ("NHWC", "HWIO", "NHWC") or groups != 1:
        return False
    if tuple(strides) != (1, 1) or tuple(lhs_dilation) != (1, 1) \
            or tuple(rhs_dilation) != (1, 1):
        return False
    kh, kw, cin, _ = w.shape
    if kh == 1 and kw == 1:
        return False        # 1x1 IS already a matmul to XLA
    return cin <= 128       # where the conv path measured slow


def conv_im2col(x, w, padding):
    """NHWC stride-1 conv as patch-extraction + one matmul (exact).
    lax.conv_general_dilated_patches emits channel-major (c, kh, kw)
    patch features; weights are transposed to match."""
    kh, kw, cin, cout = w.shape
    patches = lax.conv_general_dilated_patches(
        x, (kh, kw), (1, 1), list(map(tuple, padding)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"))   # [..., cin*kh*kw]
    wmat = jnp.transpose(w, (2, 0, 1, 3)).reshape(cin * kh * kw, cout)
    from .precision_util import contract_acc
    n, oh, ow, k = patches.shape
    out = contract_acc(jnp.dot, patches.reshape(n * oh * ow, k), wmat)
    # match the conv path's output dtype (operand promotion, NOT x.dtype:
    # bf16 activations x f32 master weights must stay f32 either way or
    # the im2col A/B would compare different-precision programs)
    return out.reshape(n, oh, ow, cout).astype(
        jnp.promote_types(x.dtype, w.dtype))


def conv_fast(x, w, strides, padding, lhs_dilation, rhs_dilation, dims,
              groups, bias=None):
    """Dispatch, highest-priority first: the Pallas implicit-GEMM kernel
    for MXU-underfilled NHWC shapes (MXTPU_PALLAS_CONV — stem/1x1/small-C
    convs, pallas/conv.py; the per-channel ``bias`` rides its fused
    epilogue), then the staged im2col lowering, then the f32-accumulate
    custom-vjp path for all-low-precision operands (when the private
    transpose helpers imported), else plain conv_general_dilated under
    the package precision policy. ``bias`` (a [C_out] vector) is applied
    on every path so callers get one set of semantics."""
    if _pallas_enabled():
        from .pallas.conv import fused_conv, pallas_applicable
        ok, _reason = pallas_applicable(x, w, strides, padding,
                                        lhs_dilation, rhs_dilation, dims,
                                        groups)
        if ok:
            # a bias whose dtype would promote the conv output (f32 bias
            # on bf16 operands) must stay an external add — the fused
            # epilogue keeps the conv dtype, and flipping the lever must
            # never change a program's output dtype
            out_dt = jnp.promote_types(x.dtype, w.dtype)
            fuse_bias = (bias is not None
                         and jnp.promote_types(out_dt, bias.dtype) == out_dt)
            out = fused_conv(x, w, strides=tuple(strides),
                             padding=tuple(map(tuple, padding)),
                             bias=bias if fuse_bias else None)
            return out if fuse_bias else _with_bias(out, bias, dims)
    if _im2col_enabled() and _im2col_applicable(
            x, w, strides, padding, lhs_dilation, rhs_dilation, dims,
            groups):
        return _with_bias(conv_im2col(x, w, padding), bias, dims)
    if (HAVE_ACC_VJP and _enabled() and x.dtype in _LOW and w.dtype in _LOW):
        return _with_bias(
            conv_acc(x, w, tuple(strides), tuple(map(tuple, padding)),
                     tuple(lhs_dilation), tuple(rhs_dilation), dims,
                     int(groups)), bias, dims)
    from .precision_util import mxu_precision
    return _with_bias(lax.conv_general_dilated(
        x, w, window_strides=strides, padding=padding,
        lhs_dilation=lhs_dilation, rhs_dilation=rhs_dilation,
        dimension_numbers=dims, feature_group_count=groups,
        precision=mxu_precision(x, w)), bias, dims)


def _with_bias(out, bias, dims):
    if bias is None:
        return out
    if dims[2][-1] == "C":          # channels-last: trailing broadcast
        return out + bias
    return out + jnp.reshape(bias, (1, -1) + (1,) * (out.ndim - 2))


def _pallas_enabled():
    """MXTPU_PALLAS_CONV=1 routes MXU-underfilled shapes through the hand
    kernel (read site: pallas/conv.py). STAGED off pending the on-chip
    resnet_pallas battery phase; in registry.policy_key."""
    import os
    return os.environ.get("MXTPU_PALLAS_CONV", "0") == "1"
