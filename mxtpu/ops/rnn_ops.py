"""Fused multi-layer (bidirectional) RNN/LSTM/GRU.

Reference: src/operator/rnn-inl.h:49 (modes kRnnRelu/kRnnTanh/kLstm/kGru) with 2.4k
LoC of hand-fused CPU kernels (rnn_impl.h) and the cuDNN path (cudnn_rnn-inl.h).

TPU-native re-design: one ``lax.scan`` over time per layer/direction — XLA compiles
the scan body (two MXU matmuls + gate nonlinearities fused on the VPU) into a single
loop executable, which is exactly what cuDNN's persistent RNN kernels hand-achieve.
The packed parameter vector layout (i2h/h2h weights then i2h/h2h biases, layer-major)
matches the reference's (rnn-inl.h GetParamSize) so checkpoints map 1:1.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .precision_util import contract_acc, mxu_precision
from .registry import (register, register_num_outputs,
                       register_param_shapes)


def _gates(mode):
    return {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}[mode]


def _gdot(x, W):
    """Gate matmul x @ W.T under the shared fast-accumulate policy
    (precision_util.contract_acc): f32 MXU accumulator for bf16 operands;
    precision still from the ACTUAL operands — weights may be bf16 while
    activations are f32, then the honest-f32 global must win."""
    return contract_acc(jnp.dot, x, W.T)


def _hoist_enabled():
    """MXTPU_RNN_HOIST=0 keeps the input projection inside the scan body
    (the pre-round-5 lowering) — escape hatch/perf A/B only; the hoist is
    algebraically identical. Trace-time policy: participates in
    registry.policy_key() so a mid-process flip recompiles long-lived
    hybridized blocks / executors instead of silently reusing the stale
    executable."""
    import os
    return os.environ.get("MXTPU_RNN_HOIST", "1") == "1"


def _precompute_xi(xs, W_ih, b_ih):
    """Hoist the input-to-hidden projection for ALL timesteps out of the
    scan: one [T*N, in] x [in, ng*H] MXU matmul instead of T small ones
    inside the loop — the cuDNN persistent-RNN "input GEMM batching"
    (cudnn_rnn-inl.h precedent), which both halves the in-scan matmul
    count and runs the hoisted half at large-matmul efficiency."""
    T, N, F = xs.shape
    xi = _gdot(xs.reshape(T * N, F), W_ih) + b_ih
    return xi.reshape(T, N, -1)


def _cell_step(mode, W_hh, b_hh, W_ih=None, b_ih=None):
    """Returns step(carry, xi_t) -> (carry, h_t) for one direction of one
    layer. xi_t is the PRECOMPUTED input projection x_t @ W_ih.T + b_ih
    (see _precompute_xi); only the recurrent matmul stays in the loop.
    When W_ih/b_ih are given (MXTPU_RNN_HOIST=0 A/B leg), the scanned
    value is the RAW x_t and the projection runs inside the body."""
    if W_ih is not None:
        inner = _cell_step(mode, W_hh, b_hh)

        def unhoisted(carry, x):
            return inner(carry, _gdot(x, W_ih) + b_ih)
        return unhoisted
    if mode == "lstm":
        def step(carry, xi):
            h, c = carry
            # precision from the ACTUAL operands (weights may be bf16 while
            # activations are f32 — then the honest-f32 global must win)
            z = xi + _gdot(h, W_hh) + b_hh
            i, f, g, o = jnp.split(z, 4, axis=-1)
            i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
            g = jnp.tanh(g)
            c_new = f * c + i * g
            h_new = o * jnp.tanh(c_new)
            return (h_new, c_new), h_new
        return step
    if mode == "gru":
        def step(carry, xi):
            h = carry
            hh = _gdot(h, W_hh) + b_hh
            xr, xz, xn = jnp.split(xi, 3, axis=-1)
            hr, hz, hn = jnp.split(hh, 3, axis=-1)
            r = jax.nn.sigmoid(xr + hr)
            z = jax.nn.sigmoid(xz + hz)
            n = jnp.tanh(xn + r * hn)
            h_new = (1 - z) * n + z * h
            return h_new, h_new
        return step
    act = jnp.tanh if mode == "rnn_tanh" else (lambda v: jnp.maximum(v, 0))

    def step(carry, xi):
        h = carry
        h_new = act(xi + _gdot(h, W_hh) + b_hh)
        return h_new, h_new
    return step


def _unpack_params(params, mode, num_layers, input_size, state_size, bidirectional,
                   projection_size=None):
    """Slice the packed parameter vector (reference layout rnn-inl.h:GetParamSize):
    all weights (layer-major, direction-major, i2h then h2h), then all biases."""
    ng = _gates(mode)
    dirs = 2 if bidirectional else 1
    idx = 0
    weights = []
    for layer in range(num_layers):
        in_sz = input_size if layer == 0 else state_size * dirs
        for d in range(dirs):
            wi_sz = ng * state_size * in_sz
            wh_sz = ng * state_size * state_size
            W_ih = params[idx:idx + wi_sz].reshape(ng * state_size, in_sz); idx += wi_sz
            W_hh = params[idx:idx + wh_sz].reshape(ng * state_size, state_size); idx += wh_sz
            weights.append([W_ih, W_hh])
    for layer in range(num_layers):
        for d in range(dirs):
            b_sz = ng * state_size
            b_ih = params[idx:idx + b_sz]; idx += b_sz
            b_hh = params[idx:idx + b_sz]; idx += b_sz
            weights[layer * dirs + d].extend([b_ih, b_hh])
    return weights


def rnn_param_size(mode, num_layers, input_size, state_size, bidirectional=False):
    ng = _gates(mode)
    dirs = 2 if bidirectional else 1
    size = 0
    for layer in range(num_layers):
        in_sz = input_size if layer == 0 else state_size * dirs
        size += dirs * ng * state_size * (in_sz + state_size + 2)
    return size


@register_num_outputs("RNN")
def _rnn_num_outputs(attrs):
    """output (+ final h, + final c for lstm) when state_outputs (ref:
    rnn.cc FNumOutputs)."""
    if not attrs.get("state_outputs"):
        return 1
    return 3 if attrs.get("mode", "lstm") == "lstm" else 2


@register("RNN")
def RNN(data, parameters, state, state_cell=None, state_size=None, num_layers=1,
        mode="lstm", bidirectional=False, p=0.0, state_outputs=False,
        projection_size=None, lstm_state_clip_min=None, lstm_state_clip_max=None,
        lstm_state_clip_nan=False, **_ig):
    """Fused RNN op (ref: src/operator/rnn.cc registration `RNN`).

    data: (T, N, input_size) — TNC like the reference. state: (L*dirs, N, H).
    Returns output (T, N, H*dirs), plus final states if state_outputs.
    """
    T, N, input_size = data.shape
    dirs = 2 if bidirectional else 1
    weights = _unpack_params(parameters, mode, num_layers, input_size, state_size,
                             bidirectional)
    h0 = state
    c0 = state_cell
    x = data
    h_finals, c_finals = [], []
    for layer in range(num_layers):
        outs = []
        for d in range(dirs):
            W_ih, W_hh, b_ih, b_hh = weights[layer * dirs + d]
            xs = x if d == 0 else jnp.flip(x, axis=0)
            if _hoist_enabled():
                step = _cell_step(mode, W_hh, b_hh)
                xi = _precompute_xi(xs, W_ih, b_ih)
            else:
                step = _cell_step(mode, W_hh, b_hh, W_ih, b_ih)
                xi = xs
            hi = h0[layer * dirs + d]
            if mode == "lstm":
                carry0 = (hi, c0[layer * dirs + d])
                (hT, cT), ys = lax.scan(step, carry0, xi)
                c_finals.append(cT)
            else:
                hT, ys = lax.scan(step, hi, xi)
            h_finals.append(hT)
            outs.append(ys if d == 0 else jnp.flip(ys, axis=0))
        x = outs[0] if dirs == 1 else jnp.concatenate(outs, axis=-1)
    out = x
    if state_outputs:
        res = [out, jnp.stack(h_finals, axis=0)]
        if mode == "lstm":
            res.append(jnp.stack(c_finals, axis=0))
        return res
    return out


@register_param_shapes("RNN")
def _rnn_param_shapes(shapes, attrs):
    """Backward fill for the fused RNN's packed inputs (ref: rnn-inl.h
    GetParamSize + FInferShape): parameters=(total,), state[/cell]
    =(L*dirs, N, H) from the TNC data shape."""
    data = shapes[0]
    if data is None:
        return {}
    T, N, input_size = data
    mode = attrs.get("mode", "lstm")
    state_size = int(attrs["state_size"])
    num_layers = int(attrs.get("num_layers", 1))
    bidirectional = bool(attrs.get("bidirectional", False))
    dirs = 2 if bidirectional else 1
    out = {1: (rnn_param_size(mode, num_layers, input_size, state_size,
                              bidirectional),),
           2: (num_layers * dirs, N, state_size)}
    if len(shapes) > 3 and mode == "lstm":
        out[3] = (num_layers * dirs, N, state_size)
    return out
