"""Monitor: per-op output statistics during execution
(ref: python/mxnet/monitor.py over MXExecutorSetMonitorCallback,
src/executor/graph_executor.cc:104)."""
from __future__ import annotations

import logging
import re

from .ndarray import NDArray

__all__ = ["Monitor", "TrainingHealthMonitor"]


class Monitor:
    """Install on executors to record a statistic of every op output each
    `interval` batches (ref: monitor.py:Monitor)."""

    def __init__(self, interval, stat_func=None, pattern=".*", sort=False):
        if stat_func is None:
            def stat_func(x):
                return x.norm() / (x.size ** 0.5)
        self.stat_func = stat_func
        self.interval = interval
        self.activated = False
        self.queue = []
        self.step = 0
        self.exes = []
        self.re_prog = re.compile(pattern)
        self.sort = sort

    def install(self, exe):
        exe.set_monitor_callback(self._stat_helper)
        self.exes.append(exe)

    def _stat_helper(self, name, value):
        if not self.activated or not self.re_prog.match(name):
            return
        self.queue.append((self.step, name, self.stat_func(value)))

    def tic(self):
        if self.step % self.interval == 0:
            for exe in self.exes:
                exe._monitor_active = True
            self.activated = True
        self.step += 1

    def toc(self):
        if not self.activated:
            return []
        self.activated = False
        for exe in self.exes:
            exe._monitor_active = False
            for name, array in getattr(exe, "output_dict", {}).items():
                if self.re_prog.match(name):
                    self.queue.append((self.step, name,
                                       self.stat_func(array)))
        res = []
        if self.sort:
            self.queue.sort(key=lambda x: x[1])
        for n, k, v_list in self.queue:
            if isinstance(v_list, NDArray):
                v_list = [v_list]
            v = ", ".join("%f" % float(v.asnumpy().reshape(-1)[0])
                          for v in v_list)
            res.append((n, k, v))
        self.queue = []
        return res

    def toc_print(self):
        for n, k, v in self.toc():
            logging.info("Batch: %7d %30s %s", n, k, v)


class TrainingHealthMonitor:
    """Surface the numerics sentinel's per-step verdicts without syncing
    the hot loop (mxtpu/resilience.py).

    The guarded fused updater buffers its async device scalars
    (step index, step_ok, global grad norm) in ``updater.health``;
    ``flush()`` materializes them in ONE batch (a single host sync, off
    the step path) and logs every skipped step. ``after_step()`` flushes
    every ``interval`` calls — the Monitor tic/toc cadence, applied to
    training health instead of op stats.

    ISSUE 14 escalations, both off the step path:

    * **Poison-batch quarantine** — ``poison_streak`` (default
      ``MXTPU_POISON_STREAK``, 0 = off) CONSECUTIVE skipped steps stop
      being a log line: the offending step indices (with their owning
      trace ids, the PR-10 step-trace attribution) land in the bounded
      ``quarantined`` ring and a ``flight_record("poison_batch")``
      artifact, and ``on_poison`` chooses ``"raise"``
      (:class:`~mxtpu.resilience.PoisonBatchError`, the default — eight
      consecutive non-finite steps is data poisoning, not overflow
      noise) vs ``"continue"`` (quarantine + keep training; the loss
      scaler keeps backing off).
    * **Divergence checks** — ``divergence_every`` (default
      ``MXTPU_DIVERGENCE_EVERY``, 0 = off) ``after_step`` calls, the
      updater's async fingerprint scalars are compared per-replica by a
      :class:`~mxtpu.resilience.DivergenceSentinel`; a mismatch dumps
      ``flight_record("divergence")`` and raises. One bounded fetch at
      check cadence — the hot loop stays sync-free."""

    def __init__(self, interval=100, logger=None, poison_streak=None,
                 on_poison="raise", divergence_every=None):
        from . import resilience
        self.interval = int(interval)
        self.logger = logger or logging.getLogger("mxtpu.resilience")
        self.poison_streak = resilience.poison_streak() \
            if poison_streak is None else int(poison_streak)
        if on_poison not in ("raise", "continue"):
            raise ValueError("on_poison must be 'raise' or 'continue', "
                             "got %r" % (on_poison,))
        self.on_poison = on_poison
        self.divergence_every = resilience.divergence_every() \
            if divergence_every is None else int(divergence_every)
        self._sentinel = resilience.DivergenceSentinel(logger=self.logger)
        self._owner = None
        self._count = 0
        self._skip_streak = 0   # consecutive skips across flushes
        self._streak = []       # the streak's (step, gnorm, trace_id)s
        self.skipped = []  # [(step, grad_norm), ...] across flushes
        import collections
        self.quarantined = collections.deque(maxlen=64)

    def install(self, owner):
        """Attach to a gluon Trainer, a Module, or a raw updater. The
        ACTIVE updater is resolved lazily at flush time: with
        update_on_kvstore the guarded steps run through the store's
        updater, and which one that is isn't known until the kvstore
        initializes on the first step."""
        self._owner = owner
        return self

    def _updater_of(self):
        owner = self._owner
        active = getattr(owner, "_active_updater", None)  # gluon Trainer
        if callable(active):
            upd = active()
            if upd is not None:
                return upd
            upds = getattr(owner, "_updaters", None)
            return upds[0] if upds else None
        if getattr(owner, "_update_on_kvstore", False) and \
                getattr(owner, "_kvstore", None) is not None:  # Module
            return owner._kvstore._updater
        upds = getattr(owner, "_updaters", None)
        if upds:
            return upds[0]
        return getattr(owner, "_updater", owner)  # Module local / raw updater

    def after_step(self):
        self._count += 1
        records = []
        if self._count % self.interval == 0:
            records = self.flush()
        if self.divergence_every > 0 and \
                self._count % self.divergence_every == 0:
            self.check_divergence()
        return records

    def check_divergence(self):
        """One per-replica fingerprint compare off the updater's async
        scalars (SYNCS on two scalars — check cadence, never the step
        path). Raises :class:`~mxtpu.resilience.DivergenceError` on a
        replicated-buffer mismatch, after the flight artifact lands."""
        updater = self._updater_of()
        fp = getattr(updater, "last_fingerprint", None)
        traces = getattr(updater, "_step_traces", None) or {}
        last_trace = next(reversed(traces.values())) \
            if hasattr(traces, "values") and traces else None
        return self._sentinel.check(
            fp, step=self._count,
            trace_ids=[last_trace] if last_trace else [])

    def flush(self):
        """Materialize buffered verdicts (syncs once); returns
        [(step, ok, grad_norm)] and logs the skipped steps.

        Every drained verdict is also emitted through the telemetry
        registry (``resilience.steps_ok`` / ``resilience.steps_skipped``
        counters, last grad-norm and live loss-scale gauges), so
        ``telemetry.report()`` shows guard activity without a log scrape.
        All telemetry updates ride the ONE batched sync drain() already
        performs — nothing extra touches the hot loop."""
        from . import telemetry
        updater = self._updater_of()
        health = getattr(updater, "health", None)
        if health is None or len(health) == 0:
            return []
        records = health.drain()
        for step, ok, gnorm in records:
            if not ok:
                self.logger.warning(
                    "step %d skipped: non-finite gradients "
                    "(global grad norm %s) — params and optimizer state "
                    "untouched, loss scale backed off", step, gnorm)
        n_skipped = sum(1 for _, ok, _ in records if not ok)
        telemetry.inc("resilience.steps_ok", len(records) - n_skipped)
        telemetry.inc("resilience.steps_skipped", n_skipped)
        telemetry.gauge("resilience.grad_norm", records[-1][2])
        scaler = getattr(updater, "scaler", None)
        if scaler is not None:
            # one more scalar on an already-syncing path (flush cadence,
            # not step cadence)
            telemetry.gauge("resilience.loss_scale", scaler.scale_value())
        self.skipped.extend((s, g) for s, ok, g in records if not ok)
        self._escalate_poison(updater, records)
        return records

    def _escalate_poison(self, updater, records):
        """Poison-batch quarantine: ``poison_streak`` CONSECUTIVE skips
        (tracked across flushes, in step order) escalate from log lines
        to a quarantine — the streak's step indices + owning trace ids
        ring-buffered and flight-recorded, then raise or continue per
        ``on_poison``. A good step resets the streak: the loss scaler
        recovering after a few backoffs is normal AMP life, a sustained
        run of non-finite steps is poisoned data."""
        if self.poison_streak <= 0:
            return
        from . import resilience, telemetry
        traces = getattr(updater, "_step_traces", None) or {}
        for step, ok, gnorm in records:
            if ok:
                self._skip_streak = 0
                self._streak = []
                continue
            self._streak.append((step, gnorm, traces.get(step)))
            self._skip_streak += 1
            if self._skip_streak < self.poison_streak:
                continue
            steps = [s for s, _, _ in self._streak]
            trace_ids = [t for _, _, t in self._streak if t]
            entry = {"steps": steps, "trace_ids": trace_ids,
                     "grad_norms": [g for _, g, _ in self._streak]}
            self.quarantined.append(entry)
            telemetry.inc("resilience.poison_quarantines")
            telemetry.flight_record("poison_batch", trace_ids=trace_ids,
                                    extra=entry)
            msg = ("poison-batch quarantine: %d CONSECUTIVE sentinel-"
                   "skipped steps (%s) — this is poisoned data or a "
                   "corrupt shard, not bf16 overflow noise; the steps' "
                   "trace ids are in the flight artifact "
                   "(reason=poison_batch)"
                   % (self._skip_streak, steps))
            self._skip_streak = 0
            self._streak = []
            if self.on_poison == "raise":
                raise resilience.PoisonBatchError(msg)
            self.logger.error("%s — continuing per on_poison='continue'",
                              msg)
