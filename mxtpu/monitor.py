"""Monitor: per-op output statistics during execution
(ref: python/mxnet/monitor.py over MXExecutorSetMonitorCallback,
src/executor/graph_executor.cc:104)."""
from __future__ import annotations

import logging
import re

from .ndarray import NDArray

__all__ = ["Monitor"]


class Monitor:
    """Install on executors to record a statistic of every op output each
    `interval` batches (ref: monitor.py:Monitor)."""

    def __init__(self, interval, stat_func=None, pattern=".*", sort=False):
        if stat_func is None:
            def stat_func(x):
                return x.norm() / (x.size ** 0.5)
        self.stat_func = stat_func
        self.interval = interval
        self.activated = False
        self.queue = []
        self.step = 0
        self.exes = []
        self.re_prog = re.compile(pattern)
        self.sort = sort

    def install(self, exe):
        exe.set_monitor_callback(self._stat_helper)
        self.exes.append(exe)

    def _stat_helper(self, name, value):
        if not self.activated or not self.re_prog.match(name):
            return
        self.queue.append((self.step, name, self.stat_func(value)))

    def tic(self):
        if self.step % self.interval == 0:
            for exe in self.exes:
                exe._monitor_active = True
            self.activated = True
        self.step += 1

    def toc(self):
        if not self.activated:
            return []
        self.activated = False
        for exe in self.exes:
            exe._monitor_active = False
            for name, array in getattr(exe, "output_dict", {}).items():
                if self.re_prog.match(name):
                    self.queue.append((self.step, name,
                                       self.stat_func(array)))
        res = []
        if self.sort:
            self.queue.sort(key=lambda x: x[1])
        for n, k, v_list in self.queue:
            if isinstance(v_list, NDArray):
                v_list = [v_list]
            v = ", ".join("%f" % float(v.asnumpy().reshape(-1)[0])
                          for v in v_list)
            res.append((n, k, v))
        self.queue = []
        return res

    def toc_print(self):
        for n, k, v in self.toc():
            logging.info("Batch: %7d %30s %s", n, k, v)
