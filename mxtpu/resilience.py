"""Resilient training runtime: numerics sentinel, loss scaling, preemption.

The reference's async engine propagates operator errors lazily
(src/engine/threaded_engine.cc) and has no story for non-finite gradients,
preempted hosts, or flaky IO — acceptable for single-job GPU training,
fatal on production TPU fleets where preemption and bf16 overflow are
routine, not exceptional. This module is the guardrail layer woven through
the existing hot path (not bolted on top of it):

* **In-jit numerics sentinel** — the fused optimizer step
  (:mod:`mxtpu.optimizer_fused`) computes ONE fused all-params finite flag
  plus the global gradient norm *inside* its donated jit and applies the
  update under ``jnp.where``: a non-finite step is a no-op on params and
  optimizer state (including the bias-correction step count ``t`` and
  momentum), with zero extra host syncs in the hot loop — the per-step
  outcome is a device ``step_ok`` scalar fetched asynchronously (the
  weight-update-sharding insight of arXiv:2004.13336, PAPERS.md: per-step
  bookkeeping belongs INSIDE the compiled program). Enable with
  ``MXTPU_NUMERICS_GUARD=1`` or by attaching a :class:`DynamicLossScaler`.
* **Dynamic loss scaling** — :class:`DynamicLossScaler` state (scale,
  good-step streak) is carried as traced device scalars through the same
  jit, so growth/backoff never recompiles and never syncs.
* **Preemption-safe checkpointing** — :class:`ResilientLoop` +
  :class:`CheckpointPolicy` drive SIGTERM/interval-triggered async orbax
  saves (``contrib/async_checkpoint.save_trainer``) with atomic
  latest-step bookkeeping, bounded retry-with-backoff on transient IO
  errors, and bit-exact resume of params + optimizer + loss-scaler + RNG.
* **Deterministic fault injection** — ``MXTPU_FAULT_INJECT`` +
  :func:`inject` hooks make every degradation path above testable on CPU
  in tier-1 (NaN grads, checkpoint IO failures, SIGTERM mid-step, dead
  dataloader workers, transient collective failures).

See ``docs/resilience.md`` for the fault -> detection -> action matrix.
"""
from __future__ import annotations

import collections
import json
import logging
import os
import random as _pyrandom
import signal
import time

from .base import MXNetError

__all__ = ["guard_enabled", "default_loss_scale", "ckpt_retries",
           "ckpt_keep", "divergence_every", "train_step_timeout_x",
           "poison_streak", "DynamicLossScaler", "StepHealth",
           "CheckpointPolicy", "ResilientLoop", "inject", "reset_faults",
           "with_retries", "FAULT_STATS", "ResourceExhausted", "maybe_oom",
           "TrainWedgeError", "TrainStepWatchdog", "DivergenceError",
           "DivergenceSentinel", "PoisonBatchError", "SupervisorRefusal",
           "TrainSupervisor"]

_log = logging.getLogger("mxtpu.resilience")


# ------------------------------------------------------------------ policies
def guard_enabled():
    """MXTPU_NUMERICS_GUARD=1 turns the in-jit sentinel on without a loss
    scaler (read per step so it can be flipped mid-process for A/Bs; the
    flip recompiles the update jit exactly once — it is part of the jit
    cache key and of ``registry.policy_key``)."""
    return os.environ.get("MXTPU_NUMERICS_GUARD", "0") == "1"


def default_loss_scale():
    """Initial loss scale (MXTPU_LOSS_SCALE, default 2**15 — the standard
    bf16/f16 AMP starting point). Host-side: the scale VALUE lives on
    device as a traced scalar and never bakes into an executable, so it
    does not belong in registry.policy_key."""
    return float(os.environ.get("MXTPU_LOSS_SCALE", str(2.0 ** 15)))  # graftlint: disable=policy-key-coverage


def ckpt_retries():
    """Transient-IO retry budget for checkpoint writes (MXTPU_CKPT_RETRIES,
    default 3). Host-side IO control flow — nothing traced."""
    return int(os.environ.get("MXTPU_CKPT_RETRIES", "3"))  # graftlint: disable=policy-key-coverage


def ckpt_keep():
    """Checkpoint retention depth (MXTPU_CKPT_KEEP, default 0 = keep
    everything): ``save_trainer`` garbage-collects finalized step
    directories older than the newest N INTACT ones. A mid-write step
    (async save not finalized) and a tombstoned (known-corrupt) step
    never count toward the keepers, so the newest restorable checkpoint
    survives even at N=1. Host-side IO policy — nothing traced."""
    return int(os.environ.get("MXTPU_CKPT_KEEP", "0") or "0")  # graftlint: disable=policy-key-coverage


def divergence_every():
    """Cross-replica divergence-sentinel cadence (MXTPU_DIVERGENCE_EVERY,
    default 0 = off). Non-zero compiles a cheap per-shard fingerprint of
    the post-update params + optimizer state (f32 sum + int32
    bitcast-fold) into the SAME donated fused-update executable — the
    on/off bit is trace-time, so it is mirrored in ``registry.policy_key``
    and the update-jit cache key (a flip is at most one recompile; the
    cadence VALUE only changes how often the host compares). The compare
    itself runs host-side off the async fingerprint scalars
    (:class:`DivergenceSentinel` / ``TrainingHealthMonitor``), adding
    zero hot-loop syncs."""
    return int(os.environ.get("MXTPU_DIVERGENCE_EVERY", "0") or "0")


def train_step_timeout_x():
    """Step-wedge watchdog multiplier (MXTPU_TRAIN_STEP_TIMEOUT_X, default
    0 = off): a Trainer.step still armed past ``baseline * X`` (rolling
    median step time) trips the wedge path — flight artifact + loud
    failure. Host-side deadline policy — nothing traced."""
    return float(os.environ.get("MXTPU_TRAIN_STEP_TIMEOUT_X", "0") or "0")  # graftlint: disable=policy-key-coverage


def poison_streak():
    """Poison-batch quarantine threshold (MXTPU_POISON_STREAK, default
    0 = off): this many CONSECUTIVE sentinel-skipped steps escalate from
    a log line to a quarantine in ``TrainingHealthMonitor`` (bounded ring
    of offending steps + trace ids, flight artifact, raise-or-continue
    policy). Host-side monitor policy — nothing traced."""
    return int(os.environ.get("MXTPU_POISON_STREAK", "0") or "0")  # graftlint: disable=policy-key-coverage


# ----------------------------------------------------------- fault injection
# fired: [(kind, index), ...] in firing order — tests assert the schedule
FAULT_STATS = {"fired": []}
_FAULT_CACHE = {"spec": None, "faults": {}}
_FAULT_COUNTERS = {}


def _parse_faults(spec):
    """``kind@i,j;kind2@k`` -> {kind: {i, j}, kind2: {k}}. Kinds in use:
    ``nan_grad`` (optimizer-step index), ``ckpt_io`` (save-attempt index),
    ``sigterm`` (loop step index), ``worker_death`` (dataloader/stream-reader
    batch index), ``prefetch_death`` (DevicePrefetcher producer pull counter
    — its own kind so composed pipelines route faults deterministically),
    ``kv_fail`` (dist-reduce attempt index), ``serve_timeout``
    (serving batch dispatch index: that batch's requests all expire),
    ``serve_overload`` (serving submit index: that submit sheds),
    ``replica_fail`` (serving dispatch index: the replica executing that
    dispatch raises — counts toward its circuit breaker), ``replica_wedge``
    (serving dispatch index: that dispatch never returns — the wedge
    watchdog quarantines the replica and re-dispatches the batch once),
    ``oom`` (occurrence index across the Trainer.step / Predictor
    dispatch / decode-loop call sites: :func:`maybe_oom` raises a
    :class:`ResourceExhausted` there, exercising the OOM flight path),
    ``train_wedge`` (Trainer.step index: that step's watchdog entry never
    disarms — the wedge scan trips, dumps ``flight_record("train_wedge")``
    and fails loud), ``ckpt_corrupt`` (save-attempt index: the saved
    updater blob's bytes are flipped AFTER the checksum manifest is
    computed, so restore verification fails exactly like real disk
    corruption and the tiered fallback engages), ``divergence``
    (divergence-check index: one fetched per-replica fingerprint shard is
    perturbed host-side, exercising the mismatch dump + raise),
    ``supervisor_crash`` (supervisor attempt index: a clean child exit is
    treated as a crash, driving the respawn/backoff/refusal matrix
    without a real failing subprocess), ``host_loss`` (fleet training
    step index: ``fleet.maybe_host_loss`` hard-exits the process with
    ``EXIT_HOST_LOSS`` before that step's collective — sudden host
    death, no cleanup), ``coordinator_loss`` (membership-check index:
    ``FleetMembership.check`` diagnoses host 0 dead and raises loud
    with the board, instead of the infinite collective hang a real dead
    coordinator causes), ``rejoin_stall`` (host rank: that host stalls
    inside ``fleet.init`` bring-up — status ``stalled``, never reaches
    the barrier — so its peers' bring-up deadline trips with the host
    named, then it exits ``EXIT_REJOIN_STALL``), ``straggler_slow``
    (fleet training step index: tools/fleet_worker.py sleeps a fixed
    slice before that step's barrier, attributed to ``data.wait`` — a
    deterministic slow host for the fleet_obs straggler sentinel to
    name)."""
    faults = {}
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        if "@" not in part:
            raise MXNetError(
                "MXTPU_FAULT_INJECT entry %r: expected kind@idx[,idx...]"
                % part)
        kind, idxs = part.split("@", 1)
        try:
            where = {int(s) for s in idxs.split(",") if s.strip()}
        except ValueError:
            raise MXNetError(
                "MXTPU_FAULT_INJECT entry %r: indices must be ints" % part)
        faults.setdefault(kind.strip(), set()).update(where)
    return faults


def inject(kind, index=None):
    """Deterministic fault-injection point: True exactly ONCE per
    (kind, index) named in ``MXTPU_FAULT_INJECT``. Call sites pass their
    natural index (step / batch / attempt); with ``index=None`` an internal
    per-kind call counter supplies it. Consuming semantics (each scheduled
    fault fires once) keep retry loops convergent by construction."""
    # host-side: faults fire in host control flow (raise/SIGTERM/skip) —
    # the nan_grad kind mutates a traced VALUE, never the traced program
    spec = os.environ.get("MXTPU_FAULT_INJECT", "")  # graftlint: disable=policy-key-coverage
    if spec != _FAULT_CACHE["spec"]:
        _FAULT_CACHE["spec"] = spec
        _FAULT_CACHE["faults"] = _parse_faults(spec) if spec else {}
        _FAULT_COUNTERS.clear()
    faults = _FAULT_CACHE["faults"]
    if index is None:
        index = _FAULT_COUNTERS.get(kind, 0)
        _FAULT_COUNTERS[kind] = index + 1
    where = faults.get(kind)
    if not where or index not in where:
        return False
    where.discard(index)
    FAULT_STATS["fired"].append((kind, index))
    from . import telemetry
    telemetry.inc("faults.injected", tag=kind)
    # an injected fault is a flight-recorder trigger: the artifact tags
    # the trace that owned the faulted call site (if any), so the
    # post-mortem starts from the affected request/step, not from grep
    ctx = telemetry.current_trace()
    telemetry.flight_record(
        "fault", trace_ids=[ctx.trace_id] if ctx is not None else [],
        extra={"kind": kind, "index": index})
    _log.warning("fault injected: %s@%d", kind, index)
    return True


def reset_faults():
    """Test hook: forget consumed faults and counters."""
    _FAULT_CACHE["spec"] = None
    _FAULT_CACHE["faults"] = {}
    _FAULT_COUNTERS.clear()
    FAULT_STATS["fired"] = []


class ResourceExhausted(RuntimeError):
    """Injected HBM OOM (fault kind ``oom``). The message mimics jaxlib's
    ``RESOURCE_EXHAUSTED`` prefix so every production matcher
    (:func:`mxtpu.xprof.is_oom`) treats it exactly like the real
    allocator failure — the OOM flight path is testable without actually
    exhausting a device."""


def maybe_oom(index=None):
    """Fault-injection point for the OOM flight path (kind ``oom``):
    raises :class:`ResourceExhausted` when ``MXTPU_FAULT_INJECT`` names
    this occurrence. Call sites: Trainer.step, Predictor dispatch, the
    decode loop — the places a real ``RESOURCE_EXHAUSTED`` surfaces."""
    if inject("oom", index):
        raise ResourceExhausted(
            "RESOURCE_EXHAUSTED: Out of memory while trying to allocate "
            "(injected fault kind 'oom')")


# ------------------------------------------------------- step-wedge watchdog
class TrainWedgeError(MXNetError):
    """A Trainer.step stayed armed past its wedge deadline (a collective
    that never completes, a dead chip under the dispatch). By the time
    this raises, the flight artifact (``flight_record("train_wedge")`` —
    per-thread stacks, the step's trace_id, the executable ledger and
    per-device memory view) is already on disk."""


class TrainStepWatchdog:
    """Per-step wedge watchdog for the training loop — the serving
    dispatch watchdog's discipline (mxtpu/serving/replicas.py) applied to
    ``Trainer.step``: every step dispatch is bracketed by an armed entry
    whose deadline derives from a ROLLING baseline of observed step
    times (``median * timeout_x``, floored at ``min_timeout_s``), so the
    bound tracks the workload instead of demanding a magic constant. A
    run that wedges in a collective currently hangs forever with no
    artifact; with the watchdog attached the trip dumps a flight record
    and fails loud.

    Drive it either way:

    * ``start_monitor()`` — an off-thread scan every ``interval``; a trip
      dumps the artifact, bumps ``train.wedges``, and poisons the
      watchdog so the NEXT arm/disarm on the training thread raises
      :class:`TrainWedgeError` (the monitor cannot raise into a thread
      blocked inside a device call — if that thread never returns, the
      artifact + log IS the loud failure, exactly the real-wedge story).
    * ``poll()`` — synchronous scan that raises on a trip; with an
      injected ``clock`` the whole matrix tests sleep-free in tier-1.

    Fault kind ``train_wedge@step`` marks the step's entry as held (its
    dispatch "never returns"): ``disarm`` leaves it armed, the clock
    advances, and the scan trips — no real hang, no sleeps.

    The bracket is pure host bookkeeping (a clock read and a list append
    per step): the ``trainer.step`` d2h==0 and retrace-flat contracts
    hold with the watchdog attached (pinned in tests)."""

    def __init__(self, timeout_x=None, min_timeout_s=1.0, window=32,
                 min_samples=3, clock=None):
        self.timeout_x = train_step_timeout_x() if timeout_x is None \
            else float(timeout_x)
        self.min_timeout_s = float(min_timeout_s)
        self.min_samples = int(min_samples)
        self._durations = collections.deque(maxlen=int(window))
        self._clock = time.monotonic if clock is None else clock
        import threading
        self._lock = threading.Lock()
        self._entries = []
        self._tripped = None   # first tripped entry: poisons arm/disarm
        self._monitor = None
        self._monitor_stop = None

    # ------------------------------------------------------------- baseline
    def baseline(self):
        """Rolling median of completed step times (None until
        ``min_samples`` — the first steps include compiles and must not
        set the bound)."""
        with self._lock:
            if len(self._durations) < self.min_samples:
                return None
            vals = sorted(self._durations)
        return vals[len(vals) // 2]

    def deadline_s(self):
        base = self.baseline()
        if base is None or self.timeout_x <= 0:
            return None
        return max(base * self.timeout_x, self.min_timeout_s)

    # ------------------------------------------------------------- bracket
    def arm(self, step, trace_id=None):
        """Arm one step's entry (call right before the dispatch). During
        warmup (no baseline yet) the entry carries no deadline — it still
        measures, it cannot trip."""
        self._check_poisoned()
        now = self._clock()
        bound = self.deadline_s()
        entry = {"step": int(step), "trace_id": trace_id, "t0": now,
                 "deadline": None if bound is None else now + bound,
                 "bound_s": bound, "tripped": False,
                 # injected wedge: this dispatch "never returns" — disarm
                 # leaves the entry armed for the scan to trip, sleep-free
                 "held": inject("train_wedge", step)}
        with self._lock:
            self._entries.append(entry)
        return entry

    def disarm(self, entry):
        """Close the bracket (finally-block of the step). Records the
        observed duration into the rolling baseline; raises if this entry
        (or the watchdog) tripped while the step ran."""
        now = self._clock()
        with self._lock:
            if entry["held"]:
                return  # simulated non-return: stays armed for the scan
            if entry in self._entries:
                self._entries.remove(entry)
                if not entry["tripped"]:
                    self._durations.append(now - entry["t0"])
        self._check_poisoned()

    # --------------------------------------------------------------- scans
    def poll(self):
        """Synchronous wedge scan — the fake-clock test drive (and usable
        from any sideline thread). Raises :class:`TrainWedgeError` on a
        trip, after the flight artifact is written."""
        tripped = self._scan()
        if tripped:
            raise TrainWedgeError(self._describe(tripped[0]))

    def _scan(self):
        now = self._clock()
        tripped = []
        with self._lock:
            for e in self._entries:
                if e["deadline"] is not None and not e["tripped"] \
                        and now > e["deadline"]:
                    e["tripped"] = True
                    tripped.append(e)
            for e in tripped:
                self._entries.remove(e)
        for e in tripped:
            self._trip(e, now)
        return tripped

    def _describe(self, e):
        return ("training step %d wedged: no completion within %.3fs "
                "(rolling baseline x %.1f); flight artifact dumped "
                "(reason=train_wedge)"
                % (e["step"], e["bound_s"] or -1.0, self.timeout_x))

    def _trip(self, e, now):
        from . import telemetry, xprof
        self._tripped = e
        telemetry.inc("train.wedges")
        # resolve-free ledger + per-device memory: the post-mortem view
        # of what was resident/compiled when the step stopped answering —
        # never invoke the compiler or block on the (possibly dead)
        # device from the trip path
        mem = {}
        try:
            import jax
            for i, d in enumerate(jax.devices()):
                mem["d%d" % i] = xprof.device_memory(d)
        except Exception:  # noqa: BLE001 — a wedged backend still dumps
            pass
        telemetry.flight_record(
            "train_wedge",
            trace_ids=[e["trace_id"]] if e["trace_id"] else [],
            extra={"step": e["step"], "elapsed_s": now - e["t0"],
                   "bound_s": e["bound_s"], "timeout_x": self.timeout_x,
                   "baseline_s": self.baseline(),
                   "ledger": xprof.ledger_snapshot(), "memory": mem})
        _log.error("%s", self._describe(e))

    def _check_poisoned(self):
        e = self._tripped
        if e is not None:
            raise TrainWedgeError(self._describe(e))

    # -------------------------------------------------------------- monitor
    def start_monitor(self, interval_s=0.25):
        """Off-thread wedge scan (idempotent). Real-clock deployments use
        this; fake-clock tests drive :meth:`poll` instead. The thread
        holds only a WEAK reference to the watchdog: a replaced/dropped
        watchdog is collectable and its orphaned monitor exits at the
        next tick instead of scanning a dead object forever."""
        import threading
        import weakref
        if self._monitor is not None and self._monitor.is_alive():
            return self
        stop = threading.Event()
        wref = weakref.ref(self)

        def loop():
            while not stop.wait(interval_s):
                wd = wref()
                if wd is None:
                    return  # the watchdog was dropped: die with it
                try:
                    wd._scan()
                except Exception:  # noqa: BLE001 — scan must never die
                    _log.exception("train-wedge monitor scan failed")
                del wd  # the loop must not pin the watchdog between ticks
        t = threading.Thread(target=loop, daemon=True,
                             name="mxtpu-train-wedge-monitor")
        self._monitor = t
        self._monitor_stop = stop
        t.start()
        return self

    def stop_monitor(self):
        if self._monitor_stop is not None:
            self._monitor_stop.set()
        if self._monitor is not None:
            self._monitor.join(timeout=2.0)
        self._monitor = None
        self._monitor_stop = None


# --------------------------------------------------- divergence sentinel
class DivergenceError(MXNetError):
    """Per-replica fingerprints of the (logically replicated) params +
    optimizer state disagree — a silent corruption forked the fleet. The
    flight artifact (``flight_record("divergence")``) carries every
    replica's fingerprint view."""


class DivergenceSentinel:
    """Host-side comparator for the in-jit divergence fingerprint.

    With ``MXTPU_DIVERGENCE_EVERY`` > 0 the fused update jit emits a
    cheap fingerprint of the post-update params + optimizer state (one
    f32 sum + one int32 bitcast-fold — the fold catches sign/NaN-payload
    flips a float sum can absorb) as replicated device scalars. XLA
    materializes a replicated output on EVERY device from that device's
    operands, so a replica whose supposedly-replicated buffers silently
    diverged computes a different copy. :meth:`check` fetches the
    per-device copies off the async scalars (``addressable_shards`` —
    the ``step_ok`` discipline: nothing in the hot loop, one bounded
    fetch at check cadence) and compares them bitwise. ZeRO-1 keeps the
    optimizer state as exactly ONE sharded copy per replica
    (arXiv:2004.13336), so this is the only watcher that state has.

    Fault kind ``divergence@i`` perturbs one fetched shard before the
    compare, exercising the dump + raise tier deterministically on any
    device count."""

    def __init__(self, logger=None):
        self._log = logger or _log
        self.checks = 0

    @staticmethod
    def _shard_views(arr):
        import numpy as np
        try:
            shards = sorted(((s.device.id, np.asarray(s.data))
                             for s in arr.addressable_shards),
                            key=lambda t: t[0])
            if shards:
                return shards
        except Exception:  # noqa: BLE001 — not a jax.Array (eager numpy)
            pass
        return [(0, np.asarray(arr))]

    def check(self, fingerprint, step=None, trace_ids=()):
        """Compare every replica's copy of the fingerprint scalars; True
        when they agree (or there is nothing to compare). SYNCS on the
        fingerprint scalars — call at check cadence, never per step."""
        from . import telemetry
        if fingerprint is None:
            return True
        telemetry.inc("resilience.divergence_checks")
        self.checks += 1
        views = []  # per component: [(device_id, bytes), ...]
        for comp in fingerprint:
            views.append([(d, v.tobytes())
                          for d, v in self._shard_views(comp)])
        if inject("divergence"):
            # a synthetic replica whose fingerprint copy disagrees —
            # appending (not replacing) keeps the injection meaningful on
            # a single-device tier too
            views[-1].append((-1, b"\xde\xad\xbe\xef"))
        ok = all(len({b for _, b in comp}) <= 1 for comp in views)
        if ok:
            return True
        detail = {"step": step,
                  "fingerprints": [[(d, b.hex()) for d, b in comp]
                                   for comp in views]}
        telemetry.flight_record("divergence", trace_ids=list(trace_ids),
                                extra=detail)
        msg = ("cross-replica divergence: per-device fingerprints of the "
               "replicated params/optimizer state disagree%s — a silent "
               "corruption forked the fleet; flight artifact dumped "
               "(reason=divergence). Restore from the last intact "
               "checkpoint." % ("" if step is None else " at check %s"
                                % step))
        self._log.error("%s", msg)
        raise DivergenceError(msg)


class PoisonBatchError(MXNetError):
    """``MXTPU_POISON_STREAK`` consecutive sentinel-skipped steps: the
    data (or a corrupt shard of it) is poisoning every step, not a
    transient overflow. The quarantine ring and flight artifact carry the
    offending step indices and their trace ids."""


# ------------------------------------------------------------------- retries
# Per-process jitter source: seeded from the pid so every process in a
# fleet draws a DIFFERENT backoff sequence (the whole point of the
# jitter), while a test passing its own seeded ``rng`` stays bit-level
# deterministic. Resolved lazily PER PID — an import-time module global
# would be copied into fork-started workers, handing the whole fleet one
# identical schedule (exactly the herd the jitter exists to prevent).
_BACKOFF = {"pid": None, "rng": None}


def _process_rng():
    pid = os.getpid()
    if _BACKOFF["pid"] != pid:
        _BACKOFF["pid"] = pid
        _BACKOFF["rng"] = _pyrandom.Random(pid * 2654435761 + 17)
    return _BACKOFF["rng"]


def _next_backoff(rng, base, prev, cap):
    """Decorrelated-jitter exponential backoff (the AWS pattern): the next
    delay is uniform in [base, 3*prev], capped. Unlike plain exponential
    backoff — where every client that failed at t=0 retries at exactly
    t+base, t+3*base, ... — the draws de-synchronize a fleet whose
    kvstore/checkpoint backend just flapped, so the retries cannot arrive
    as a thundering herd."""
    return min(cap, rng.uniform(base, max(base, prev * 3.0)))


def with_retries(fn, what, retries=None, backoff=0.25, logger=None,
                 exceptions=(Exception,), metric=None, sleeper=None,
                 rng=None, max_backoff=None):
    """Run ``fn`` with bounded retry-with-backoff on transient failures.

    Used by the checkpoint driver and the kvstore's DCN reduce. Retries
    ``retries`` times (default :func:`ckpt_retries`); the last failure
    re-raises so hard errors stay loud. The first retry waits exactly
    ``backoff`` seconds; later waits use decorrelated jitter
    (:func:`_next_backoff`, capped at ``max_backoff``, default
    ``64*backoff``) so fleet-wide retries against one flapping backend
    cannot synchronize into a thundering herd. ``sleeper``/``rng`` are
    injectable: tests run sleep-free and bit-deterministic.

    Every retry counts into the telemetry registry: ``retry.total``
    always, plus the caller's stable ``metric`` name (``what`` often
    carries per-call detail like a step number — unusable as a metric
    key), so transient-IO flakiness shows up in ``telemetry.report()``
    without a log scrape."""
    from . import telemetry
    retries = ckpt_retries() if retries is None else int(retries)
    retries = max(0, retries)  # a negative budget must still run fn once
    sleeper = time.sleep if sleeper is None else sleeper
    rng = _process_rng() if rng is None else rng
    cap = backoff * 64.0 if max_backoff is None else float(max_backoff)
    delay = backoff
    for attempt in range(retries + 1):
        try:
            return fn()
        except exceptions as e:
            if attempt == retries:
                raise
            telemetry.inc("retry.total")
            if metric:
                telemetry.inc(metric)
            (logger or _log).warning(
                "%s failed (%s: %s); retry %d/%d in %.2fs", what,
                type(e).__name__, e, attempt + 1, retries, delay)
            sleeper(delay)
            delay = _next_backoff(rng, backoff, delay, cap)


# --------------------------------------------------------------- loss scaler
class DynamicLossScaler:
    """Dynamic bf16/f16 loss scaling driven by the in-jit sentinel.

    The scale and the good-step streak live as DEVICE scalars and are
    updated inside the fused optimizer jit: on a non-finite step the scale
    backs off by ``backoff_factor``; after ``growth_interval`` consecutive
    good steps it grows by ``growth_factor`` (clamped to
    [min_scale, max_scale]). No host syncs, and a schedule change never
    recompiles — only the STATIC config tuple below is baked into the jit.

    Usage with the gluon Trainer::

        scaler = resilience.DynamicLossScaler()
        trainer = gluon.Trainer(params, "sgd", {...}, loss_scaler=scaler)
        with autograd.record():
            loss = scaler.scale(loss_fn(net(x), y))
        loss.backward()          # grads come out scale-times too large
        trainer.step(batch)      # unscaled + guarded inside the fused jit

    State is serialized with the optimizer state (Trainer.save_states /
    contrib.async_checkpoint.save_trainer), so resume is bit-exact.
    """

    def __init__(self, init_scale=None, growth_factor=2.0,
                 backoff_factor=0.5, growth_interval=2000,
                 max_scale=2.0 ** 24, min_scale=1.0):
        self._init = (default_loss_scale() if init_scale is None
                      else float(init_scale))
        self.growth_factor = float(growth_factor)
        self.backoff_factor = float(backoff_factor)
        self.growth_interval = int(growth_interval)
        self.max_scale = float(max_scale)
        self.min_scale = float(min_scale)
        # lazy device scalars: materializing them would initialize the XLA
        # backend at construction time (random.py has the same constraint)
        self._scale = None
        self._streak = None

    def config(self):
        """The STATIC policy tuple baked into the guarded jit (part of its
        cache key — changing the schedule recompiles once; the scale value
        itself is traced and never does)."""
        return (self.growth_factor, self.backoff_factor,
                self.growth_interval, self.max_scale, self.min_scale)

    def _ensure(self):
        if self._scale is None:
            import jax.numpy as jnp
            self._scale = jnp.float32(self._init)
            self._streak = jnp.int32(0)

    def scale_array(self):
        """The live scale as a device scalar (async — no host sync)."""
        self._ensure()
        return self._scale

    def scale_value(self):
        """The live scale as a python float (SYNCS — debugging/tests)."""
        return float(self.scale_array())

    def scale(self, loss):
        """``loss * scale`` (an async device multiply; record()-taped, so
        gradients come out scale-times larger and the guarded updater
        divides the scale back out in-jit). The multiply stays in the
        scale's f32 — casting the scale into a float16 loss would overflow
        to inf past 2**16 — so the scaled loss promotes to float32 (exact,
        and .backward() is dtype-agnostic)."""
        from .ndarray import NDArray
        self._ensure()
        return loss * NDArray(self._scale)

    def host_update(self, ok):
        """Eager-path bookkeeping (sparse/unfusable optimizers): the same
        growth/backoff rule, driven by a host bool. Device arithmetic stays
        async."""
        import jax.numpy as jnp
        self._ensure()
        if ok:
            self._streak = self._streak + 1
            grown = jnp.clip(self._scale * self.growth_factor,
                             self.min_scale, self.max_scale)
            do_grow = self._streak >= self.growth_interval
            self._scale = jnp.where(do_grow, grown, self._scale)
            self._streak = jnp.where(do_grow, 0, self._streak)
        else:
            self._scale = jnp.clip(self._scale * self.backoff_factor,
                                   self.min_scale, self.max_scale)
            self._streak = jnp.int32(0)

    # ------------------------------------------------------------- serialize
    def state_dict(self):
        import numpy as np
        self._ensure()
        return {"scale": np.asarray(self._scale),
                "streak": np.asarray(self._streak),
                "config": (self._init,) + self.config()}

    def load_state_dict(self, state):
        import jax.numpy as jnp
        self._scale = jnp.float32(float(state["scale"]))
        self._streak = jnp.int32(int(state["streak"]))

    @classmethod
    def from_state_dict(cls, state):
        init, gf, bf, gi, mx, mn = state["config"]
        scaler = cls(init_scale=init, growth_factor=gf, backoff_factor=bf,
                     growth_interval=gi, max_scale=mx, min_scale=mn)
        scaler.load_state_dict(state)
        return scaler


# ------------------------------------------------------------------- health
class StepHealth:
    """Ring buffer of per-step (step, step_ok, grad_norm) DEVICE scalars.

    The guarded updater appends the not-yet-materialized jit outputs here;
    nothing syncs until a reader asks (``ok_history``/``drain``), keeping
    the hot loop transfer-free while still giving monitors and tests the
    full skip history."""

    def __init__(self, maxlen=4096):
        self._buf = collections.deque(maxlen=maxlen)

    def append(self, step, ok, grad_norm):
        self._buf.append((step, ok, grad_norm))

    def __len__(self):
        return len(self._buf)

    def steps(self):
        return [s for s, _, _ in self._buf]

    @staticmethod
    def _fetch(values):
        # ONE batched device_get instead of a blocking round trip per
        # scalar — a flush over hundreds of buffered steps costs one stall
        import jax
        return jax.device_get(list(values))

    def ok_history(self):
        """Materialize the step_ok flags (SYNCS once — call off the hot
        path)."""
        return [bool(ok) for ok in self._fetch(o for _, o, _ in self._buf)]

    def grad_norm_history(self):
        return [float(g) for g in self._fetch(g for _, _, g in self._buf)]

    def drain(self):
        """Pop and materialize everything buffered: [(step, ok, gnorm)] —
        one batched fetch, not one sync per step."""
        steps = [s for s, _, _ in self._buf]
        fetched = self._fetch((o, g) for _, o, g in self._buf)
        self._buf.clear()
        return [(s, bool(o), float(g))
                for s, (o, g) in zip(steps, fetched)]

    def clear(self):
        self._buf.clear()


# -------------------------------------------------------------- checkpoints
class CheckpointPolicy:
    """When and how :class:`ResilientLoop` checkpoints.

    ``every_steps``/``every_secs`` trigger interval saves (either may be
    None); ``retries``/``backoff`` bound the retry-with-backoff on
    transient IO errors (default MXTPU_CKPT_RETRIES); ``async_save`` uses
    the shared orbax AsyncCheckpointer so training continues while the
    write completes."""

    def __init__(self, directory, every_steps=None, every_secs=None,
                 async_save=True, retries=None, backoff=0.25):
        self.directory = str(directory)
        self.every_steps = every_steps
        self.every_secs = every_secs
        self.async_save = bool(async_save)
        self.retries = retries
        self.backoff = float(backoff)

    def due(self, step, last_save_step, last_save_time):
        if self.every_steps and step - last_save_step >= self.every_steps:
            return True
        if self.every_secs and \
                time.monotonic() - last_save_time >= self.every_secs:
            return True
        return False


def _sigterm_postmortem():
    """Off-handler SIGTERM postmortem: flight-record the kill, then force
    a final telemetry flush — the off-thread sink flusher is a daemon, so
    a SIGTERM'd host would otherwise lose its last buffered window of
    metrics (exactly the window a straggler/crash postmortem needs). Runs
    on a daemon thread; the signal handler itself stays IO-free."""
    from . import telemetry
    telemetry.flight_record("sigterm")
    telemetry.flush()


class ResilientLoop:
    """Preemption-safe training driver around a gluon Trainer.

    Installs SIGTERM handling (flag set in the handler, acted on at the
    next step boundary: final async checkpoint, then a clean stop),
    interval-triggered async checkpoints with bounded retry, atomic
    latest-step bookkeeping (``latest.json`` written tmp+rename and
    VALIDATED on read — an async save that never finalized falls back to
    the newest finalized step directory), and bit-exact resume of
    params + optimizer + loss-scaler + RNG state::

        loop = resilience.ResilientLoop(trainer, CheckpointPolicy(
            "/ckpt/run1", every_steps=100))
        start = loop.resume()            # 0 on a fresh directory
        loop.run(step_fn, num_steps, start_step=start)
        if loop.preempted: ...           # stopped on SIGTERM, ckpt written
    """

    def __init__(self, trainer, policy, signals=(signal.SIGTERM,),
                 logger=None):
        self._trainer = trainer
        self._policy = policy
        self._signals = tuple(signals)
        self._log = logger or _log
        self._prev_handlers = {}
        self._installed = False
        self.preempted = False
        self.last_saved_step = None
        self._last_save_step = -1
        self._last_save_time = time.monotonic()
        self._last_ckptr = None
        self._step = 0

    # ---------------------------------------------------------------- signals
    def install(self):
        """Install signal handlers (idempotent; main thread only — off the
        main thread python refuses handlers, so this degrades to manual
        ``loop.preempted = True``)."""
        if self._installed:
            return self
        try:
            for sig in self._signals:
                self._prev_handlers[sig] = signal.signal(sig, self._on_signal)
            self._installed = True
        except ValueError:  # not the main thread
            self._log.warning(
                "ResilientLoop: cannot install signal handlers off the main "
                "thread; set loop.preempted=True manually to request a stop")
        return self

    def uninstall(self):
        for sig, prev in self._prev_handlers.items():
            signal.signal(sig, prev)
        self._prev_handlers = {}
        self._installed = False

    def __enter__(self):
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()
        return False

    def _on_signal(self, signum, frame):
        # handler does the MINIMUM (no IO, no jax): the step boundary acts;
        # the flight-recorder snapshot (a SIGTERM trigger) runs on its own
        # daemon thread so the handler stays IO-free
        self.preempted = True
        import threading

        threading.Thread(target=_sigterm_postmortem,
                         daemon=True, name="mxtpu-flight-sigterm").start()

    # ---------------------------------------------------------------- saving
    def save(self, step, final=False):
        """Checkpoint now, with bounded retry-with-backoff. Interval saves
        degrade gracefully (log + keep training) when every retry fails;
        ``final=True`` (the preemption save) blocks until the write is
        durable and re-raises on total failure."""
        from .contrib import async_checkpoint as ackpt

        def _save():
            ck = ackpt.save_trainer(
                self._trainer, self._policy.directory, step=step,
                async_save=self._policy.async_save and not final, force=True)
            if final and hasattr(ck, "wait_until_finished"):
                ck.wait_until_finished()
            return ck

        try:
            self._last_ckptr = with_retries(
                _save, "checkpoint save (step %d)" % step,
                retries=(ckpt_retries() if self._policy.retries
                         is None else self._policy.retries),
                backoff=self._policy.backoff, logger=self._log,
                metric="retry.checkpoint_save")
        except Exception as e:
            if final:
                raise
            self._log.error(
                "checkpoint at step %d failed after retries (%s: %s); "
                "training continues — the previous checkpoint stays latest "
                "and the next attempt waits a full interval (a retry storm "
                "on every step would stall training for the whole outage)",
                step, type(e).__name__, e)
            self._last_save_step = step
            self._last_save_time = time.monotonic()
            return False
        self._write_latest(step)
        self._last_save_step = step
        self._last_save_time = time.monotonic()
        self.last_saved_step = step
        return True

    def wait_for_pending(self):
        """Block until the last async checkpoint write is durable (a
        finalized step directory). Interval saves return before the write
        completes; call this before shutdown or before trusting
        :meth:`latest_step` in the same process."""
        if self._last_ckptr is not None and \
                hasattr(self._last_ckptr, "wait_until_finished"):
            self._last_ckptr.wait_until_finished()

    def _write_latest(self, step):
        """Atomic latest-step pointer: a crash mid-write must never leave a
        torn pointer. Local dirs use tmp + os.replace; URL-style dirs
        (gs://, s3:// — the production checkpoint home) write the object
        directly through epath, where a small-object PUT is itself atomic."""
        payload = json.dumps({"step": int(step)})
        directory = self._policy.directory
        if "://" in directory:
            from etils import epath
            d = epath.Path(directory)
            d.mkdir(parents=True, exist_ok=True)
            (d / "latest.json").write_text(payload)
            return
        os.makedirs(directory, exist_ok=True)
        path = os.path.join(directory, "latest.json")
        # pid-unique tmp: in fleet mode several hosts share the checkpoint
        # dir, and two writers racing one ".tmp" name can rename a torn
        # file into place — each pid stages its own and os.replace stays
        # last-writer-wins-atomic
        tmp = "%s.%d.tmp" % (path, os.getpid())
        with open(tmp, "w") as f:
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def latest_step(self):
        """Newest RESUMABLE step (None on a fresh directory) — the shared
        ``contrib.async_checkpoint.latest_step`` scan: latest.json when its
        step dir finalized, else the newest finalized ``step_*`` dir,
        epath-routed so gs://-style directories resume from a fresh host."""
        from .contrib import async_checkpoint as ackpt
        return ackpt.latest_step(self._policy.directory)

    def resume(self):
        """Restore the newest INTACT checkpoint into the trainer (params +
        optimizer + scaler + RNG, bit-exact) and return the step index to
        continue FROM (0 on a fresh directory). Tiered: a step whose
        checksum manifest does not verify (or whose restore errors) is
        tombstoned and the next-newest finalized step is tried —
        ``checkpoint.restore_fallbacks{reason}`` counts every tier
        crossed (``contrib.async_checkpoint.load_trainer_fallback``)."""
        from .contrib import async_checkpoint as ackpt
        step = ackpt.load_trainer_fallback(self._trainer,
                                           self._policy.directory)
        if step is None:
            return 0
        self._step = step + 1
        self._last_save_step = step
        self._log.info("resumed from checkpoint step %d", step)
        return step + 1

    # --------------------------------------------------------------- driving
    def after_step(self, step):
        """Call once per completed optimizer step. Handles fault injection,
        interval checkpoints, and the preemption save. Returns True when
        the loop should stop (final checkpoint already written)."""
        self._step = step + 1
        if inject("sigterm", step):
            os.kill(os.getpid(), signal.SIGTERM)  # handler runs immediately
        if self.preempted:
            self._log.warning(
                "preemption signal received: writing final checkpoint at "
                "step %d", step)
            self.save(step, final=True)
            return True
        if self._policy.due(step, self._last_save_step,
                            self._last_save_time):
            self.save(step)
        return False

    def run(self, step_fn, num_steps, start_step=None):
        """Drive ``step_fn(step)`` for ``range(start, num_steps)`` with
        signal handlers installed; returns the last executed step index
        (or start-1 when there was nothing to do)."""
        start = self._step if start_step is None else int(start_step)
        last = start - 1
        with self:
            for step in range(start, num_steps):
                step_fn(step)
                last = step
                if self.after_step(step):
                    break
        return last


# ------------------------------------------------------ crash-resume driver
class SupervisorRefusal(MXNetError):
    """The supervisor will not respawn: either the same checkpoint step
    crashed twice in a row (a deterministic poison-crash — restarting
    replays it forever) or the crash-loop budget is spent. The message is
    the diagnosis. By the time this raises, a
    ``flight_record("supervisor_refusal")`` artifact carrying the
    diagnosis and the full restart ``history`` is on disk (see
    :func:`_refuse`)."""


def _refuse(diagnosis, history, logger=None):
    """Build a :class:`SupervisorRefusal` the evidence-first way: dump a
    ``flight_record("supervisor_refusal")`` artifact carrying the
    diagnosis and the supervisor's full restart ``history`` BEFORE the
    exception exists — a crash-looped fleet leaves a post-mortem
    artifact, not just an exception string in a dead tty. Shared by
    :class:`TrainSupervisor` and ``fleet.FleetSupervisor``; callers
    ``raise _refuse(...)``."""
    from . import telemetry
    telemetry.flight_record(
        "supervisor_refusal",
        extra={"diagnosis": diagnosis, "history": list(history)})
    (logger or _log).error("supervisor refusal: %s", diagnosis)
    return SupervisorRefusal(diagnosis)


class TrainSupervisor:
    """Crash-resume supervisor around a training entrypoint (the CLI
    front door is ``tools/train_supervisor.py``).

    Respawns the child on a nonzero exit with decorrelated-jitter
    exponential backoff (:func:`_next_backoff` — a fleet of supervisors
    must not re-stampede a recovering storage/coordinator backend) under
    a crash-loop budget (``MXTPU_SUPERVISOR_RESTARTS``). The child is
    expected to resume itself from the integrity-verified newest intact
    checkpoint (``ResilientLoop.resume`` — tombstoned/corrupt steps are
    already skipped by the tiered restore); the supervisor reads the same
    ``latest_step`` view per attempt to DIAGNOSE: a crash at the same
    checkpoint step as the previous crash means resuming cannot help
    (poison-crash — a batch or code path that deterministically kills
    the process past the numerics sentinel), so it refuses with that
    diagnosis instead of flapping forever; crashes with checkpoint
    progress in between are transient and respawn.

    ``spawn``/``clock``/``sleeper``/``rng`` are injectable so the whole
    loop tests sleep-free and subprocess-free in tier-1. Fault kind
    ``supervisor_crash@attempt`` turns that attempt's clean exit into a
    simulated crash."""

    def __init__(self, argv, ckpt_dir=None, max_restarts=None,
                 backoff_s=None, max_backoff_s=60.0, spawn=None,
                 clock=None, sleeper=None, rng=None, logger=None):
        self.argv = list(argv)
        if not self.argv:
            raise MXNetError("TrainSupervisor needs a non-empty command")
        self.ckpt_dir = ckpt_dir
        # host-side supervisor policy, nothing traced
        if max_restarts is None:
            max_restarts = os.environ.get("MXTPU_SUPERVISOR_RESTARTS", "8")  # graftlint: disable=policy-key-coverage
        if backoff_s is None:
            backoff_s = os.environ.get("MXTPU_SUPERVISOR_BACKOFF_S", "2.0")  # graftlint: disable=policy-key-coverage
        self.max_restarts = int(max_restarts)
        self.backoff_s = float(backoff_s)
        self.max_backoff_s = float(max_backoff_s)
        self._spawn = self._default_spawn if spawn is None else spawn
        self._clock = time.monotonic if clock is None else clock
        self._sleeper = time.sleep if sleeper is None else sleeper
        self._rng = rng  # None -> the per-pid fleet rng, resolved at use
        self._log = logger or _log
        self.restarts = 0
        self.history = []  # [(attempt, exit_code, resume_step, delay_s)]

    @staticmethod
    def _default_spawn(argv):
        import subprocess
        return subprocess.call(argv)

    def _latest(self):
        """The newest INTACT checkpoint step (tombstoned/unfinalized steps
        excluded — the same view the child's tiered resume uses), or None
        without a checkpoint directory / on a fresh one."""
        if self.ckpt_dir is None:
            return None
        from .contrib import async_checkpoint as ackpt
        try:
            return ackpt.latest_step(self.ckpt_dir)
        except Exception:  # noqa: BLE001 — a broken dir reads as fresh
            return None

    def run(self):
        """Drive the child until a clean exit (returns 0) or a refusal
        (:class:`SupervisorRefusal` with the diagnosis)."""
        from . import telemetry
        delay = self.backoff_s
        prev_crash_step = ()  # sentinel: no crash observed yet
        attempt = 0
        while True:
            resume_step = self._latest()
            self._log.info(
                "supervisor: launching attempt %d (resume step %s): %s",
                attempt, resume_step, " ".join(self.argv))
            rc = self._spawn(self.argv)
            reason = "crash"
            if rc == 0:
                if inject("supervisor_crash", attempt):
                    rc, reason = 1, "injected"
                else:
                    self._log.info("supervisor: clean exit after %d "
                                   "restart(s)", self.restarts)
                    return 0
            crash_step = self._latest()
            self.history.append((attempt, rc, crash_step, delay))
            # the poison test needs a real progress SIGNAL: with no
            # checkpoint dir (or before the first checkpoint ever lands)
            # crash_step is None on every attempt — indistinguishable
            # crashes must stay "transient" under the budget, not
            # misdiagnose as a deterministic poison-crash after one try
            if crash_step is not None and crash_step == prev_crash_step:
                raise _refuse(
                    "the child crashed twice at checkpoint step %s with "
                    "ZERO progress in between (exit code %d) — this is a "
                    "deterministic poison-crash (a batch/code path that "
                    "kills the process on replay), not a transient fault "
                    "(those advance the checkpoint between crashes). "
                    "Refusing to respawn: inspect the flight artifacts "
                    "and quarantine ring for the poisoned step before "
                    "restarting by hand." % (crash_step, rc),
                    self.history, self._log)
            if self.restarts >= self.max_restarts:
                raise _refuse(
                    "crash-loop budget spent: %d restarts "
                    "(MXTPU_SUPERVISOR_RESTARTS) with the child still "
                    "dying (last exit code %d, last checkpoint step %s) "
                    "— refusing to flap further" %
                    (self.restarts, rc, crash_step),
                    self.history, self._log)
            prev_crash_step = crash_step
            self.restarts += 1
            attempt += 1
            telemetry.inc("supervisor.restarts", tag=reason)
            self._log.warning(
                "supervisor: child exited %d (checkpoint step %s); "
                "respawn %d/%d in %.2fs", rc, crash_step, self.restarts,
                self.max_restarts, delay)
            self._sleeper(delay)
            delay = _next_backoff(self._rng or _process_rng(),
                                  self.backoff_s, delay,
                                  self.max_backoff_s)
