"""Resilient training runtime: numerics sentinel, loss scaling, preemption.

The reference's async engine propagates operator errors lazily
(src/engine/threaded_engine.cc) and has no story for non-finite gradients,
preempted hosts, or flaky IO — acceptable for single-job GPU training,
fatal on production TPU fleets where preemption and bf16 overflow are
routine, not exceptional. This module is the guardrail layer woven through
the existing hot path (not bolted on top of it):

* **In-jit numerics sentinel** — the fused optimizer step
  (:mod:`mxtpu.optimizer_fused`) computes ONE fused all-params finite flag
  plus the global gradient norm *inside* its donated jit and applies the
  update under ``jnp.where``: a non-finite step is a no-op on params and
  optimizer state (including the bias-correction step count ``t`` and
  momentum), with zero extra host syncs in the hot loop — the per-step
  outcome is a device ``step_ok`` scalar fetched asynchronously (the
  weight-update-sharding insight of arXiv:2004.13336, PAPERS.md: per-step
  bookkeeping belongs INSIDE the compiled program). Enable with
  ``MXTPU_NUMERICS_GUARD=1`` or by attaching a :class:`DynamicLossScaler`.
* **Dynamic loss scaling** — :class:`DynamicLossScaler` state (scale,
  good-step streak) is carried as traced device scalars through the same
  jit, so growth/backoff never recompiles and never syncs.
* **Preemption-safe checkpointing** — :class:`ResilientLoop` +
  :class:`CheckpointPolicy` drive SIGTERM/interval-triggered async orbax
  saves (``contrib/async_checkpoint.save_trainer``) with atomic
  latest-step bookkeeping, bounded retry-with-backoff on transient IO
  errors, and bit-exact resume of params + optimizer + loss-scaler + RNG.
* **Deterministic fault injection** — ``MXTPU_FAULT_INJECT`` +
  :func:`inject` hooks make every degradation path above testable on CPU
  in tier-1 (NaN grads, checkpoint IO failures, SIGTERM mid-step, dead
  dataloader workers, transient collective failures).

See ``docs/resilience.md`` for the fault -> detection -> action matrix.
"""
from __future__ import annotations

import collections
import json
import logging
import os
import signal
import time

from .base import MXNetError

__all__ = ["guard_enabled", "default_loss_scale", "ckpt_retries",
           "DynamicLossScaler", "StepHealth", "CheckpointPolicy",
           "ResilientLoop", "inject", "reset_faults", "with_retries",
           "FAULT_STATS", "ResourceExhausted", "maybe_oom"]

_log = logging.getLogger("mxtpu.resilience")


# ------------------------------------------------------------------ policies
def guard_enabled():
    """MXTPU_NUMERICS_GUARD=1 turns the in-jit sentinel on without a loss
    scaler (read per step so it can be flipped mid-process for A/Bs; the
    flip recompiles the update jit exactly once — it is part of the jit
    cache key and of ``registry.policy_key``)."""
    return os.environ.get("MXTPU_NUMERICS_GUARD", "0") == "1"


def default_loss_scale():
    """Initial loss scale (MXTPU_LOSS_SCALE, default 2**15 — the standard
    bf16/f16 AMP starting point). Host-side: the scale VALUE lives on
    device as a traced scalar and never bakes into an executable, so it
    does not belong in registry.policy_key."""
    return float(os.environ.get("MXTPU_LOSS_SCALE", str(2.0 ** 15)))  # graftlint: disable=policy-key-coverage


def ckpt_retries():
    """Transient-IO retry budget for checkpoint writes (MXTPU_CKPT_RETRIES,
    default 3). Host-side IO control flow — nothing traced."""
    return int(os.environ.get("MXTPU_CKPT_RETRIES", "3"))  # graftlint: disable=policy-key-coverage


# ----------------------------------------------------------- fault injection
# fired: [(kind, index), ...] in firing order — tests assert the schedule
FAULT_STATS = {"fired": []}
_FAULT_CACHE = {"spec": None, "faults": {}}
_FAULT_COUNTERS = {}


def _parse_faults(spec):
    """``kind@i,j;kind2@k`` -> {kind: {i, j}, kind2: {k}}. Kinds in use:
    ``nan_grad`` (optimizer-step index), ``ckpt_io`` (save-attempt index),
    ``sigterm`` (loop step index), ``worker_death`` (dataloader/stream-reader
    batch index), ``prefetch_death`` (DevicePrefetcher producer pull counter
    — its own kind so composed pipelines route faults deterministically),
    ``kv_fail`` (dist-reduce attempt index), ``serve_timeout``
    (serving batch dispatch index: that batch's requests all expire),
    ``serve_overload`` (serving submit index: that submit sheds),
    ``replica_fail`` (serving dispatch index: the replica executing that
    dispatch raises — counts toward its circuit breaker), ``replica_wedge``
    (serving dispatch index: that dispatch never returns — the wedge
    watchdog quarantines the replica and re-dispatches the batch once),
    ``oom`` (occurrence index across the Trainer.step / Predictor
    dispatch / decode-loop call sites: :func:`maybe_oom` raises a
    :class:`ResourceExhausted` there, exercising the OOM flight path)."""
    faults = {}
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        if "@" not in part:
            raise MXNetError(
                "MXTPU_FAULT_INJECT entry %r: expected kind@idx[,idx...]"
                % part)
        kind, idxs = part.split("@", 1)
        try:
            where = {int(s) for s in idxs.split(",") if s.strip()}
        except ValueError:
            raise MXNetError(
                "MXTPU_FAULT_INJECT entry %r: indices must be ints" % part)
        faults.setdefault(kind.strip(), set()).update(where)
    return faults


def inject(kind, index=None):
    """Deterministic fault-injection point: True exactly ONCE per
    (kind, index) named in ``MXTPU_FAULT_INJECT``. Call sites pass their
    natural index (step / batch / attempt); with ``index=None`` an internal
    per-kind call counter supplies it. Consuming semantics (each scheduled
    fault fires once) keep retry loops convergent by construction."""
    # host-side: faults fire in host control flow (raise/SIGTERM/skip) —
    # the nan_grad kind mutates a traced VALUE, never the traced program
    spec = os.environ.get("MXTPU_FAULT_INJECT", "")  # graftlint: disable=policy-key-coverage
    if spec != _FAULT_CACHE["spec"]:
        _FAULT_CACHE["spec"] = spec
        _FAULT_CACHE["faults"] = _parse_faults(spec) if spec else {}
        _FAULT_COUNTERS.clear()
    faults = _FAULT_CACHE["faults"]
    if index is None:
        index = _FAULT_COUNTERS.get(kind, 0)
        _FAULT_COUNTERS[kind] = index + 1
    where = faults.get(kind)
    if not where or index not in where:
        return False
    where.discard(index)
    FAULT_STATS["fired"].append((kind, index))
    from . import telemetry
    telemetry.inc("faults.injected", tag=kind)
    # an injected fault is a flight-recorder trigger: the artifact tags
    # the trace that owned the faulted call site (if any), so the
    # post-mortem starts from the affected request/step, not from grep
    ctx = telemetry.current_trace()
    telemetry.flight_record(
        "fault", trace_ids=[ctx.trace_id] if ctx is not None else [],
        extra={"kind": kind, "index": index})
    _log.warning("fault injected: %s@%d", kind, index)
    return True


def reset_faults():
    """Test hook: forget consumed faults and counters."""
    _FAULT_CACHE["spec"] = None
    _FAULT_CACHE["faults"] = {}
    _FAULT_COUNTERS.clear()
    FAULT_STATS["fired"] = []


class ResourceExhausted(RuntimeError):
    """Injected HBM OOM (fault kind ``oom``). The message mimics jaxlib's
    ``RESOURCE_EXHAUSTED`` prefix so every production matcher
    (:func:`mxtpu.xprof.is_oom`) treats it exactly like the real
    allocator failure — the OOM flight path is testable without actually
    exhausting a device."""


def maybe_oom(index=None):
    """Fault-injection point for the OOM flight path (kind ``oom``):
    raises :class:`ResourceExhausted` when ``MXTPU_FAULT_INJECT`` names
    this occurrence. Call sites: Trainer.step, Predictor dispatch, the
    decode loop — the places a real ``RESOURCE_EXHAUSTED`` surfaces."""
    if inject("oom", index):
        raise ResourceExhausted(
            "RESOURCE_EXHAUSTED: Out of memory while trying to allocate "
            "(injected fault kind 'oom')")


# ------------------------------------------------------------------- retries
def with_retries(fn, what, retries=None, backoff=0.25, logger=None,
                 exceptions=(Exception,), metric=None):
    """Run ``fn`` with bounded retry-with-backoff on transient failures.

    Used by the checkpoint driver and the kvstore's DCN reduce. Retries
    ``retries`` times (default :func:`ckpt_retries`) with exponential
    backoff starting at ``backoff`` seconds; the last failure re-raises so
    hard errors stay loud.

    Every retry counts into the telemetry registry: ``retry.total``
    always, plus the caller's stable ``metric`` name (``what`` often
    carries per-call detail like a step number — unusable as a metric
    key), so transient-IO flakiness shows up in ``telemetry.report()``
    without a log scrape."""
    from . import telemetry
    retries = ckpt_retries() if retries is None else int(retries)
    retries = max(0, retries)  # a negative budget must still run fn once
    delay = backoff
    for attempt in range(retries + 1):
        try:
            return fn()
        except exceptions as e:
            if attempt == retries:
                raise
            telemetry.inc("retry.total")
            if metric:
                telemetry.inc(metric)
            (logger or _log).warning(
                "%s failed (%s: %s); retry %d/%d in %.2fs", what,
                type(e).__name__, e, attempt + 1, retries, delay)
            time.sleep(delay)
            delay *= 2


# --------------------------------------------------------------- loss scaler
class DynamicLossScaler:
    """Dynamic bf16/f16 loss scaling driven by the in-jit sentinel.

    The scale and the good-step streak live as DEVICE scalars and are
    updated inside the fused optimizer jit: on a non-finite step the scale
    backs off by ``backoff_factor``; after ``growth_interval`` consecutive
    good steps it grows by ``growth_factor`` (clamped to
    [min_scale, max_scale]). No host syncs, and a schedule change never
    recompiles — only the STATIC config tuple below is baked into the jit.

    Usage with the gluon Trainer::

        scaler = resilience.DynamicLossScaler()
        trainer = gluon.Trainer(params, "sgd", {...}, loss_scaler=scaler)
        with autograd.record():
            loss = scaler.scale(loss_fn(net(x), y))
        loss.backward()          # grads come out scale-times too large
        trainer.step(batch)      # unscaled + guarded inside the fused jit

    State is serialized with the optimizer state (Trainer.save_states /
    contrib.async_checkpoint.save_trainer), so resume is bit-exact.
    """

    def __init__(self, init_scale=None, growth_factor=2.0,
                 backoff_factor=0.5, growth_interval=2000,
                 max_scale=2.0 ** 24, min_scale=1.0):
        self._init = (default_loss_scale() if init_scale is None
                      else float(init_scale))
        self.growth_factor = float(growth_factor)
        self.backoff_factor = float(backoff_factor)
        self.growth_interval = int(growth_interval)
        self.max_scale = float(max_scale)
        self.min_scale = float(min_scale)
        # lazy device scalars: materializing them would initialize the XLA
        # backend at construction time (random.py has the same constraint)
        self._scale = None
        self._streak = None

    def config(self):
        """The STATIC policy tuple baked into the guarded jit (part of its
        cache key — changing the schedule recompiles once; the scale value
        itself is traced and never does)."""
        return (self.growth_factor, self.backoff_factor,
                self.growth_interval, self.max_scale, self.min_scale)

    def _ensure(self):
        if self._scale is None:
            import jax.numpy as jnp
            self._scale = jnp.float32(self._init)
            self._streak = jnp.int32(0)

    def scale_array(self):
        """The live scale as a device scalar (async — no host sync)."""
        self._ensure()
        return self._scale

    def scale_value(self):
        """The live scale as a python float (SYNCS — debugging/tests)."""
        return float(self.scale_array())

    def scale(self, loss):
        """``loss * scale`` (an async device multiply; record()-taped, so
        gradients come out scale-times larger and the guarded updater
        divides the scale back out in-jit). The multiply stays in the
        scale's f32 — casting the scale into a float16 loss would overflow
        to inf past 2**16 — so the scaled loss promotes to float32 (exact,
        and .backward() is dtype-agnostic)."""
        from .ndarray import NDArray
        self._ensure()
        return loss * NDArray(self._scale)

    def host_update(self, ok):
        """Eager-path bookkeeping (sparse/unfusable optimizers): the same
        growth/backoff rule, driven by a host bool. Device arithmetic stays
        async."""
        import jax.numpy as jnp
        self._ensure()
        if ok:
            self._streak = self._streak + 1
            grown = jnp.clip(self._scale * self.growth_factor,
                             self.min_scale, self.max_scale)
            do_grow = self._streak >= self.growth_interval
            self._scale = jnp.where(do_grow, grown, self._scale)
            self._streak = jnp.where(do_grow, 0, self._streak)
        else:
            self._scale = jnp.clip(self._scale * self.backoff_factor,
                                   self.min_scale, self.max_scale)
            self._streak = jnp.int32(0)

    # ------------------------------------------------------------- serialize
    def state_dict(self):
        import numpy as np
        self._ensure()
        return {"scale": np.asarray(self._scale),
                "streak": np.asarray(self._streak),
                "config": (self._init,) + self.config()}

    def load_state_dict(self, state):
        import jax.numpy as jnp
        self._scale = jnp.float32(float(state["scale"]))
        self._streak = jnp.int32(int(state["streak"]))

    @classmethod
    def from_state_dict(cls, state):
        init, gf, bf, gi, mx, mn = state["config"]
        scaler = cls(init_scale=init, growth_factor=gf, backoff_factor=bf,
                     growth_interval=gi, max_scale=mx, min_scale=mn)
        scaler.load_state_dict(state)
        return scaler


# ------------------------------------------------------------------- health
class StepHealth:
    """Ring buffer of per-step (step, step_ok, grad_norm) DEVICE scalars.

    The guarded updater appends the not-yet-materialized jit outputs here;
    nothing syncs until a reader asks (``ok_history``/``drain``), keeping
    the hot loop transfer-free while still giving monitors and tests the
    full skip history."""

    def __init__(self, maxlen=4096):
        self._buf = collections.deque(maxlen=maxlen)

    def append(self, step, ok, grad_norm):
        self._buf.append((step, ok, grad_norm))

    def __len__(self):
        return len(self._buf)

    def steps(self):
        return [s for s, _, _ in self._buf]

    @staticmethod
    def _fetch(values):
        # ONE batched device_get instead of a blocking round trip per
        # scalar — a flush over hundreds of buffered steps costs one stall
        import jax
        return jax.device_get(list(values))

    def ok_history(self):
        """Materialize the step_ok flags (SYNCS once — call off the hot
        path)."""
        return [bool(ok) for ok in self._fetch(o for _, o, _ in self._buf)]

    def grad_norm_history(self):
        return [float(g) for g in self._fetch(g for _, _, g in self._buf)]

    def drain(self):
        """Pop and materialize everything buffered: [(step, ok, gnorm)] —
        one batched fetch, not one sync per step."""
        steps = [s for s, _, _ in self._buf]
        fetched = self._fetch((o, g) for _, o, g in self._buf)
        self._buf.clear()
        return [(s, bool(o), float(g))
                for s, (o, g) in zip(steps, fetched)]

    def clear(self):
        self._buf.clear()


# -------------------------------------------------------------- checkpoints
class CheckpointPolicy:
    """When and how :class:`ResilientLoop` checkpoints.

    ``every_steps``/``every_secs`` trigger interval saves (either may be
    None); ``retries``/``backoff`` bound the retry-with-backoff on
    transient IO errors (default MXTPU_CKPT_RETRIES); ``async_save`` uses
    the shared orbax AsyncCheckpointer so training continues while the
    write completes."""

    def __init__(self, directory, every_steps=None, every_secs=None,
                 async_save=True, retries=None, backoff=0.25):
        self.directory = str(directory)
        self.every_steps = every_steps
        self.every_secs = every_secs
        self.async_save = bool(async_save)
        self.retries = retries
        self.backoff = float(backoff)

    def due(self, step, last_save_step, last_save_time):
        if self.every_steps and step - last_save_step >= self.every_steps:
            return True
        if self.every_secs and \
                time.monotonic() - last_save_time >= self.every_secs:
            return True
        return False


class ResilientLoop:
    """Preemption-safe training driver around a gluon Trainer.

    Installs SIGTERM handling (flag set in the handler, acted on at the
    next step boundary: final async checkpoint, then a clean stop),
    interval-triggered async checkpoints with bounded retry, atomic
    latest-step bookkeeping (``latest.json`` written tmp+rename and
    VALIDATED on read — an async save that never finalized falls back to
    the newest finalized step directory), and bit-exact resume of
    params + optimizer + loss-scaler + RNG state::

        loop = resilience.ResilientLoop(trainer, CheckpointPolicy(
            "/ckpt/run1", every_steps=100))
        start = loop.resume()            # 0 on a fresh directory
        loop.run(step_fn, num_steps, start_step=start)
        if loop.preempted: ...           # stopped on SIGTERM, ckpt written
    """

    def __init__(self, trainer, policy, signals=(signal.SIGTERM,),
                 logger=None):
        self._trainer = trainer
        self._policy = policy
        self._signals = tuple(signals)
        self._log = logger or _log
        self._prev_handlers = {}
        self._installed = False
        self.preempted = False
        self.last_saved_step = None
        self._last_save_step = -1
        self._last_save_time = time.monotonic()
        self._last_ckptr = None
        self._step = 0

    # ---------------------------------------------------------------- signals
    def install(self):
        """Install signal handlers (idempotent; main thread only — off the
        main thread python refuses handlers, so this degrades to manual
        ``loop.preempted = True``)."""
        if self._installed:
            return self
        try:
            for sig in self._signals:
                self._prev_handlers[sig] = signal.signal(sig, self._on_signal)
            self._installed = True
        except ValueError:  # not the main thread
            self._log.warning(
                "ResilientLoop: cannot install signal handlers off the main "
                "thread; set loop.preempted=True manually to request a stop")
        return self

    def uninstall(self):
        for sig, prev in self._prev_handlers.items():
            signal.signal(sig, prev)
        self._prev_handlers = {}
        self._installed = False

    def __enter__(self):
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()
        return False

    def _on_signal(self, signum, frame):
        # handler does the MINIMUM (no IO, no jax): the step boundary acts;
        # the flight-recorder snapshot (a SIGTERM trigger) runs on its own
        # daemon thread so the handler stays IO-free
        self.preempted = True
        import threading

        from . import telemetry
        threading.Thread(target=telemetry.flight_record, args=("sigterm",),
                         daemon=True, name="mxtpu-flight-sigterm").start()

    # ---------------------------------------------------------------- saving
    def save(self, step, final=False):
        """Checkpoint now, with bounded retry-with-backoff. Interval saves
        degrade gracefully (log + keep training) when every retry fails;
        ``final=True`` (the preemption save) blocks until the write is
        durable and re-raises on total failure."""
        from .contrib import async_checkpoint as ackpt

        def _save():
            ck = ackpt.save_trainer(
                self._trainer, self._policy.directory, step=step,
                async_save=self._policy.async_save and not final, force=True)
            if final and hasattr(ck, "wait_until_finished"):
                ck.wait_until_finished()
            return ck

        try:
            self._last_ckptr = with_retries(
                _save, "checkpoint save (step %d)" % step,
                retries=(ckpt_retries() if self._policy.retries
                         is None else self._policy.retries),
                backoff=self._policy.backoff, logger=self._log,
                metric="retry.checkpoint_save")
        except Exception as e:
            if final:
                raise
            self._log.error(
                "checkpoint at step %d failed after retries (%s: %s); "
                "training continues — the previous checkpoint stays latest "
                "and the next attempt waits a full interval (a retry storm "
                "on every step would stall training for the whole outage)",
                step, type(e).__name__, e)
            self._last_save_step = step
            self._last_save_time = time.monotonic()
            return False
        self._write_latest(step)
        self._last_save_step = step
        self._last_save_time = time.monotonic()
        self.last_saved_step = step
        return True

    def wait_for_pending(self):
        """Block until the last async checkpoint write is durable (a
        finalized step directory). Interval saves return before the write
        completes; call this before shutdown or before trusting
        :meth:`latest_step` in the same process."""
        if self._last_ckptr is not None and \
                hasattr(self._last_ckptr, "wait_until_finished"):
            self._last_ckptr.wait_until_finished()

    def _write_latest(self, step):
        """Atomic latest-step pointer: a crash mid-write must never leave a
        torn pointer. Local dirs use tmp + os.replace; URL-style dirs
        (gs://, s3:// — the production checkpoint home) write the object
        directly through epath, where a small-object PUT is itself atomic."""
        payload = json.dumps({"step": int(step)})
        directory = self._policy.directory
        if "://" in directory:
            from etils import epath
            d = epath.Path(directory)
            d.mkdir(parents=True, exist_ok=True)
            (d / "latest.json").write_text(payload)
            return
        os.makedirs(directory, exist_ok=True)
        path = os.path.join(directory, "latest.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def latest_step(self):
        """Newest RESUMABLE step (None on a fresh directory) — the shared
        ``contrib.async_checkpoint.latest_step`` scan: latest.json when its
        step dir finalized, else the newest finalized ``step_*`` dir,
        epath-routed so gs://-style directories resume from a fresh host."""
        from .contrib import async_checkpoint as ackpt
        return ackpt.latest_step(self._policy.directory)

    def resume(self):
        """Restore the newest checkpoint into the trainer (params +
        optimizer + scaler + RNG, bit-exact) and return the step index to
        continue FROM (0 on a fresh directory)."""
        from .contrib import async_checkpoint as ackpt
        step = self.latest_step()
        if step is None:
            return 0
        ackpt.load_trainer(self._trainer, self._policy.directory, step=step)
        self._step = step + 1
        self._last_save_step = step
        self._log.info("resumed from checkpoint step %d", step)
        return step + 1

    # --------------------------------------------------------------- driving
    def after_step(self, step):
        """Call once per completed optimizer step. Handles fault injection,
        interval checkpoints, and the preemption save. Returns True when
        the loop should stop (final checkpoint already written)."""
        self._step = step + 1
        if inject("sigterm", step):
            os.kill(os.getpid(), signal.SIGTERM)  # handler runs immediately
        if self.preempted:
            self._log.warning(
                "preemption signal received: writing final checkpoint at "
                "step %d", step)
            self.save(step, final=True)
            return True
        if self._policy.due(step, self._last_save_step,
                            self._last_save_time):
            self.save(step)
        return False

    def run(self, step_fn, num_steps, start_step=None):
        """Drive ``step_fn(step)`` for ``range(start, num_steps)`` with
        signal handlers installed; returns the last executed step index
        (or start-1 when there was nothing to do)."""
        start = self._step if start_step is None else int(start_step)
        last = start - 1
        with self:
            for step in range(start, num_steps):
                step_fn(step)
                last = step
                if self.after_step(step):
                    break
        return last
