"""Custom operators with python callbacks (ref: python/mxnet/operator.py,
kernel plumbing src/operator/custom/custom-inl.h + custom.cc).

The reference runs user python code on a dedicated CustomOperator worker
thread woven into the async engine so the callback can't deadlock the
dependency scheduler. The TPU-native equivalent is ``jax.pure_callback``:
XLA compiles a host-callback custom-call, the runtime ships device buffers
to the host, the user's numpy code runs, and results stream back — working
identically under eager dispatch, CachedOp/hybridize, and Symbol executors
because they all lower through the same registry op. The gradient is a
``jax.custom_vjp`` whose backward is a second pure_callback into the user's
``CustomOp.backward``.

API parity: ``CustomOp``/``CustomOpProp``/``operator.register`` and
``mx.nd.Custom(*data, op_type=...)`` match the reference surface
(operator.py:426-640).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .base import MXNetError

__all__ = ["CustomOp", "CustomOpProp", "register", "get"]

_PROPS = {}


class CustomOp:
    """User-defined forward/backward on numpy-like NDArrays
    (ref: operator.py:426)."""

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise NotImplementedError

    def assign(self, dst, req, src):
        """Write src into dst honoring the grad request
        (ref: operator.py:463)."""
        if req in ("null", None):
            return
        if req == "add":
            dst[:] = dst[:] + src
        else:
            dst[:] = src


class CustomOpProp:
    """Op metadata: arguments, outputs, shapes, types
    (ref: operator.py:472). ``need_top_grad`` defaults True like the
    reference (loss-style ops set it False)."""

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = need_top_grad

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def list_auxiliary_states(self):
        return []

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]] * len(self.list_outputs()), []

    def infer_type(self, in_type):
        return (in_type, [in_type[0]] * len(self.list_outputs()),
                [in_type[0]] * len(self.list_auxiliary_states()))

    def create_operator(self, ctx, in_shapes, in_dtypes):
        return CustomOp()


def register(reg_name):
    """Decorator registering a CustomOpProp subclass under op_type=reg_name
    (ref: operator.py:register)."""
    def deco(prop_cls):
        if not issubclass(prop_cls, CustomOpProp):
            raise MXNetError("register expects a CustomOpProp subclass")
        _PROPS[reg_name] = prop_cls
        return prop_cls
    return deco


def get(op_type):
    if op_type not in _PROPS:
        raise MXNetError(
            "custom op %r is not registered; use "
            "@mxtpu.operator.register(%r) on a CustomOpProp" % (op_type,
                                                                op_type))
    return _PROPS[op_type]


class _HostArray:
    """The numpy view handed to user forward/backward — quacks enough like
    an NDArray (asnumpy, shape, dtype, slice-assign) for reference-style op
    code to run unchanged."""

    def __init__(self, arr):
        self._np = np.asarray(arr)

    def asnumpy(self):
        return self._np

    @property
    def shape(self):
        return self._np.shape

    @property
    def dtype(self):
        return self._np.dtype

    def __getitem__(self, idx):
        return self._np[idx]

    def __setitem__(self, idx, val):
        self._np[idx] = np.asarray(val._np if isinstance(val, _HostArray)
                                   else val)


def _custom_fn(op_type, n_inputs, **attrs):
    """Build the jnp-level function for one Custom invocation signature."""
    prop_cls = get(op_type)
    kwargs = {k: str(v) for k, v in attrs.items()}
    try:
        prop = prop_cls(**kwargs)
    except TypeError:
        prop = prop_cls()
    n_outputs = len(prop.list_outputs())

    def _shapes_dtypes(in_datas):
        in_shapes = [list(d.shape) for d in in_datas]
        _, out_shapes, _ = prop.infer_shape(in_shapes)
        in_types = [d.dtype for d in in_datas]
        _, out_types, _ = prop.infer_type(in_types)
        return [jax.ShapeDtypeStruct(tuple(s), t)
                for s, t in zip(out_shapes, out_types)]

    def _make_op(in_datas):
        return prop.create_operator(
            None, [list(d.shape) for d in in_datas],
            [d.dtype for d in in_datas])

    @jax.custom_vjp
    def fn(*in_datas):
        out_sds = _shapes_dtypes(in_datas)

        def host_fwd(*arrs):
            op = _make_op(arrs)
            ins = [_HostArray(a) for a in arrs]
            outs = [_HostArray(np.zeros(s.shape, s.dtype)) for s in out_sds]
            op.forward(True, ["write"] * len(outs), ins, outs, [])
            return tuple(o._np for o in outs)

        out = jax.pure_callback(host_fwd, tuple(out_sds), *in_datas,
                                vmap_method="sequential")
        return out[0] if n_outputs == 1 else list(out)

    def fwd(*in_datas):
        return fn(*in_datas), in_datas

    def bwd(in_datas, cots):
        out_sds = _shapes_dtypes(in_datas)
        cots = [cots] if n_outputs == 1 else list(cots)
        in_sds = tuple(jax.ShapeDtypeStruct(d.shape, d.dtype)
                       for d in in_datas)

        def host_bwd(*arrs):
            ins = [_HostArray(a) for a in arrs[:n_inputs]]
            gouts = [_HostArray(a) for a in arrs[n_inputs:]]
            op = _make_op(arrs[:n_inputs])
            # recompute forward outputs for ops whose backward reads them
            outs = [_HostArray(np.zeros(s.shape, s.dtype)) for s in out_sds]
            op.forward(True, ["write"] * len(outs), ins, outs, [])
            gins = [_HostArray(np.zeros(a.shape, a.dtype))
                    for a in arrs[:n_inputs]]
            op.backward(["write"] * len(gins), gouts, ins, outs, gins, [])
            return tuple(g._np for g in gins)

        gin = jax.pure_callback(host_bwd, in_sds, *(list(in_datas) + cots),
                                vmap_method="sequential")
        return tuple(gin)

    fn.defvjp(fwd, bwd)
    return fn


@functools.lru_cache(maxsize=None)
def _cached_custom_fn(op_type, n_inputs, attr_items):
    return _custom_fn(op_type, n_inputs, **dict(attr_items))


def _invoke(op_type, data, attrs):
    """Entry point for the registry-level `Custom` op (mxtpu/ops/custom.py)."""
    if op_type is None:
        raise MXNetError("Custom requires op_type=")
    fn = _cached_custom_fn(op_type, len(data), tuple(sorted(attrs.items())))
    return fn(*data)
