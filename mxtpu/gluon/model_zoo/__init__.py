"""Model zoo (ref: python/mxnet/gluon/model_zoo/__init__.py)."""
from . import vision  # noqa: F401
from . import transformer  # noqa: F401  (TPU-first long-context family)
