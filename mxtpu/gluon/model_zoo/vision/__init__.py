"""Vision model zoo (ref: python/mxnet/gluon/model_zoo/vision/__init__.py).

`get_model(name, **kwargs)` resolves any of the reference's model names.
Pretrained weights are not bundled (the reference downloads them from S3);
use `net.load_parameters(path)` with locally stored weights.
"""
from ....base import MXNetError
# import modules before star-imports: the `alexnet` function from the star
# import shadows the `alexnet` submodule attribute on this package
from . import alexnet as _alexnet
from . import densenet as _densenet
from . import inception as _inception
from . import mobilenet as _mobilenet
from . import resnet as _resnet
from . import squeezenet as _squeezenet
from . import vgg as _vgg
from .alexnet import *  # noqa: F401,F403
from .densenet import *  # noqa: F401,F403
from .inception import *  # noqa: F401,F403
from .mobilenet import *  # noqa: F401,F403
from .resnet import *  # noqa: F401,F403
from .squeezenet import *  # noqa: F401,F403
from .vgg import *  # noqa: F401,F403

_models = {}
for _mod in (_alexnet, _densenet, _inception, _mobilenet, _resnet, _squeezenet,
             _vgg):
    for _name in _mod.__all__:
        _obj = getattr(_mod, _name)
        if callable(_obj) and _name[0].islower() and not _name.startswith("get_"):
            _models[_name] = _obj


def get_model(name, **kwargs):
    """Return a model by name (ref: model_zoo/vision/__init__.py:get_model).
    Accepts the reference's dotted multiplier spellings ('mobilenet1.0',
    'squeezenet1.0') as well as the underscore form."""
    name = name.lower().replace(".", "_")
    if name not in _models:
        raise MXNetError(
            "model %s not supported; available: %s" % (name, sorted(_models)))
    return _models[name](**kwargs)
