"""Transformer language model — the long-context flagship.

The reference predates transformers (its only attention helper is
``_contrib_div_sqrt_dim``, src/operator/contrib/transformer.cc; SURVEY §5
records long-context support as absent). This model family is therefore a
TPU-first addition: a pre-norm decoder-only LM whose attention runs as ring
attention (:mod:`mxtpu.parallel.ring_attention`) when a mesh with a sequence
axis is supplied, so context length scales linearly with the `sp` mesh axis.

Parallelism axes, all expressible in one ShardedTrainStep:
* batch over ``data``,
* sequence over ``sp`` (K/V ring over ICI),
* MLP / attention projections over ``model`` via PartitionSpec rules
  (:func:`tensor_parallel_rules`).
"""
from __future__ import annotations

from jax.sharding import PartitionSpec as P

from ...base import MXNetError
from ..block import HybridBlock
from .. import nn

__all__ = ["TransformerLM", "TransformerBlock", "MultiHeadSelfAttention",
           "tensor_parallel_rules", "expert_parallel_rules"]


class MultiHeadSelfAttention(HybridBlock):
    """Causal multi-head self-attention; ring-parallel over `sp` when a mesh
    is given."""

    def __init__(self, dim, num_heads, mesh=None, seq_axis="sp",
                 batch_axis="data", causal=True, **kwargs):
        super().__init__(**kwargs)
        if dim % num_heads:
            raise MXNetError("dim %d not divisible by num_heads %d"
                             % (dim, num_heads))
        self._dim = dim
        self._heads = num_heads
        self._mesh = mesh
        self._seq_axis = seq_axis
        self._batch_axis = batch_axis
        self._causal = causal
        with self.name_scope():
            self.qkv = nn.Dense(3 * dim, use_bias=False, flatten=False,
                                prefix="qkv_")
            self.proj = nn.Dense(dim, use_bias=False, flatten=False,
                                 prefix="proj_")

    def hybrid_forward(self, F, x):
        b, t, _ = x.shape
        h, d = self._heads, self._dim // self._heads
        qkv = self.qkv(x)                                  # [B, T, 3C]
        qkv = F.reshape(qkv, (b, t, 3, h, d))
        qkv = F.transpose(qkv, (2, 0, 3, 1, 4))            # [3, B, H, T, D]
        q, k, v = qkv[0], qkv[1], qkv[2]
        from ...parallel.ring_attention import ring_attention_nd
        out = ring_attention_nd(q, k, v, mesh=self._mesh,
                                seq_axis=self._seq_axis,
                                batch_axis=self._batch_axis,
                                causal=self._causal)       # [B, H, T, D]
        out = F.reshape(F.transpose(out, (0, 2, 1, 3)), (b, t, self._dim))
        return self.proj(out)


class TransformerBlock(HybridBlock):
    """Pre-norm block: x + attn(ln(x)); x + mlp(ln(x))."""

    def __init__(self, dim, num_heads, hidden_mult=4, mesh=None,
                 seq_axis="sp", batch_axis="data", causal=True,
                 num_experts=0, capacity_factor=1.25, **kwargs):
        super().__init__(**kwargs)
        self._moe = num_experts > 0
        with self.name_scope():
            self.ln1 = nn.LayerNorm()
            self.attn = MultiHeadSelfAttention(
                dim, num_heads, mesh=mesh, seq_axis=seq_axis,
                batch_axis=batch_axis, causal=causal, prefix="attn_")
            self.ln2 = nn.LayerNorm()
            if self._moe:
                from ..contrib.nn import SwitchMoE
                self.moe = SwitchMoE(dim, hidden_mult * dim, num_experts,
                                     capacity_factor=capacity_factor,
                                     prefix="moe_")
            else:
                self.fc1 = nn.Dense(hidden_mult * dim, flatten=False,
                                    activation="relu", prefix="mlp1_")
                self.fc2 = nn.Dense(dim, flatten=False, prefix="mlp2_")

    def hybrid_forward(self, F, x):
        x = x + self.attn(self.ln1(x))
        if self._moe:
            out, aux = self.moe(self.ln2(x))
            self._last_aux = aux  # summed by TransformerLM.aux_loss()
            return x + out
        return x + self.fc2(self.fc1(self.ln2(x)))


class TransformerLM(HybridBlock):
    """Decoder-only LM: embed → N blocks → LayerNorm → vocab head.

    Input: int token ids [B, T]; output: logits [B, T, vocab].
    ``causal=False`` gives the bidirectional (BERT-style encoder) variant —
    the same trunk the masked-LM pretraining benchmark drives.
    """

    def __init__(self, vocab_size, dim=256, num_heads=8, num_layers=2,
                 max_len=2048, hidden_mult=4, mesh=None, seq_axis="sp",
                 batch_axis="data", causal=True, num_experts=0,
                 capacity_factor=1.25, **kwargs):
        super().__init__(**kwargs)
        self._vocab = vocab_size
        self._max_len = max_len
        with self.name_scope():
            self.embed = nn.Embedding(vocab_size, dim, prefix="wte_")
            self.pos_embed = nn.Embedding(max_len, dim, prefix="wpe_")
            self.blocks = nn.HybridSequential(prefix="h_")
            with self.blocks.name_scope():
                for _ in range(num_layers):
                    self.blocks.add(TransformerBlock(
                        dim, num_heads, hidden_mult=hidden_mult, mesh=mesh,
                        seq_axis=seq_axis, batch_axis=batch_axis,
                        causal=causal, num_experts=num_experts,
                        capacity_factor=capacity_factor))
            self.ln_f = nn.LayerNorm()
            self.head = nn.Dense(vocab_size, use_bias=False, flatten=False,
                                 prefix="head_")

    def hybrid_forward(self, F, tokens):
        t = tokens.shape[-1]
        if t > self._max_len:
            raise MXNetError(
                "sequence length %d exceeds max_len %d (positions would be "
                "clamped to the last positional embedding)" % (t, self._max_len))
        pos = F.arange(0, t, dtype="int32")
        x = self.embed(tokens) + self.pos_embed(pos)
        x = self.blocks(x)
        return self.head(self.ln_f(x))

    def aux_loss(self):
        """Sum of the Switch load-balancing losses of this forward (MoE
        blocks only; 0.0 for the dense model). Add scaled by your alpha.

        Consume it in the SAME trace as the forward that produced it —
        e.g. inside a ShardedTrainStep ``forward`` or an autograd.record
        scope. Do NOT net.hybridize() the MoE variant and read aux_loss
        afterwards: the compiled CachedOp returns only the logits, so the
        attribute would hold a stale trace-time value (the SwitchMoE LAYER
        returns (out, aux) explicitly for that usage instead)."""
        total = None
        any_moe = False
        for blk in self.blocks:
            any_moe = any_moe or getattr(blk, "_moe", False)
            aux = getattr(blk, "_last_aux", None)
            if aux is not None:
                total = aux if total is None else total + aux
        from ..block import _IN_TRACE, _active_trace
        if (any_moe and getattr(self, "_active", False)
                and _active_trace() is None and _IN_TRACE.active == 0):
            # compiled CachedOp forwards never refresh _last_aux — reading
            # it here would silently return the trace-time constant. Inside
            # an active trace forward() bypasses the CachedOp (block.py),
            # so _last_aux IS fresh there and reading it is supported.
            raise MXNetError(
                "aux_loss() on a hybridized MoE TransformerLM would return "
                "a stale trace-time value; compute the loss inside the "
                "traced forward (use the SwitchMoE layer's (out, aux) "
                "return) or call aux_loss() before hybridize()")
        if any_moe and total is None:
            raise MXNetError(
                "aux_loss() before any forward: no load-balancing loss has "
                "been recorded yet")
        return 0.0 if total is None else total


def tensor_parallel_rules(model_axis="model"):
    """PartitionSpec rules sharding the FLOP-heavy projections over the model
    axis (Dense weights are [units, in]: dim 0 = column-parallel, dim 1 =
    row-parallel, Megatron-style pairing so activations stay sharded through
    the MLP)."""
    return [
        (r".*qkv_weight", P(model_axis, None)),
        (r".*proj_weight", P(None, model_axis)),
        (r".*mlp1_weight", P(model_axis, None)),
        (r".*mlp2_weight", P(None, model_axis)),
        (r".*head_weight", P(model_axis, None)),
        (r".*wte_weight", P(None, model_axis)),
    ]


def expert_parallel_rules(expert_axis="expert"):
    """PartitionSpec rules for the MoE variant (num_experts > 0): the
    expert-stacked FFN weights shard on their leading E axis — GSPMD then
    lowers the dispatch/combine einsums to all-to-all over the axis."""
    return [
        (r".*moe_w1", P(expert_axis)),
        (r".*moe_b1", P(expert_axis)),
        (r".*moe_w2", P(expert_axis)),
        (r".*moe_b2", P(expert_axis)),
    ]
