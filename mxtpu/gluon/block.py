"""Block / HybridBlock: the neural-network composition layer.

Reference: ``python/mxnet/gluon/block.py:127-954`` — ``Block`` (eager container with
child/parameter registration), ``HybridBlock`` (``hybridize()`` swaps the imperative
forward for a cached compiled graph via ``CachedOp``, block.py:750-797), and
``SymbolBlock`` (:954).

TPU-native re-design of ``CachedOp`` (src/imperative/cached_op.h:83): instead of
caching an nnvm graph and re-executing it through the engine, ``hybridize()`` traces
the block's forward into a *pure jax function of (inputs, params, rng-key)* and
compiles it with ``jax.jit`` — XLA's ahead-of-time compilation IS the reference's
``static_alloc/static_shape`` mode (memory planning, op fusion and scheduling are the
compiler's job, SURVEY §7 stage 3). The jit cache is keyed per input
signature (shape/dtype/tree structure), which reproduces the reference's
per-shape graph re-planning (``CachedOp::SetForwardGraph``) and the
BucketingModule-style bucketed compile cache for dynamic shapes.

Mutable state stays functional under the trace:

* parameters enter as traced arguments (``_TraceFrame.param_map``),
* aux state (BatchNorm moving stats) is collected via ``_TraceFrame.aux_updates``
  and written back after the compiled call returns,
* RNG draws split from a per-call key argument (mxtpu/random.py key supply), so a
  compiled Dropout stays stochastic across steps.

Training mode integrates with the autograd tape by recording the whole compiled
forward as ONE taped node whose vjp is captured at call time (``jax.vjp`` of the
jitted function — forward and transpose both run as compiled executables), the
analog of ``CachedOp::Backward`` executing the cached backward graph.
"""
from __future__ import annotations

import re
import threading
from collections import OrderedDict

import jax
import jax.numpy as jnp

from .. import autograd
from .. import random as _random
from .. import telemetry
from ..base import MXNetError, current_context, numeric_types
from ..ndarray import NDArray
from .parameter import (DeferredInitializationError, Parameter, ParameterDict,
                        _TraceFrame, _TRACE, _active_trace)

__all__ = ["Block", "HybridBlock", "SymbolBlock", "CachedOp"]


# ------------------------------------------------------------------ tree utils
def _flatten_nd(args, fmt):
    """Flatten nested tuples/lists of NDArrays (the CachedOp input-flattening,
    ref: python/mxnet/gluon/block.py:_flatten)."""
    if isinstance(args, NDArray):
        fmt.append(0)
        return [args]
    if args is None:
        fmt.append(-1)
        return []
    if isinstance(args, (list, tuple)):
        fmt.append(len(args))
        flat = []
        for a in args:
            flat.extend(_flatten_nd(a, fmt))
        return flat
    fmt.append(-2)
    return [args]  # opaque static (scalar/str); kept positionally


def _regroup(flat, fmt, pos=0, idx=0):
    """Inverse of _flatten_nd; returns (value, new_pos, new_idx)."""
    code = fmt[idx]
    if code == 0 or code == -2:
        return flat[pos], pos + 1, idx + 1
    if code == -1:
        return None, pos, idx + 1
    items = []
    idx += 1
    for _ in range(code):
        v, pos, idx = _regroup(flat, fmt, pos, idx)
        items.append(v)
    return tuple(items), pos, idx


class _InTrace(threading.local):
    def __init__(self):
        self.active = 0


_IN_TRACE = _InTrace()


def _run_traced(params, param_datas, rng_key, train, body):
    """Execute `body()` (imperative mxtpu code) as a pure traced region:
    each Parameter in `params` reads from the matching entry of `param_datas`,
    RNG draws split from `rng_key`, autograd taping is off, and BatchNorm-style
    aux writes are collected functionally. Returns (result, aux_updates list
    aligned with params). Single source of truth for CachedOp and
    mxtpu.parallel.ShardedTrainStep."""
    frame = _TraceFrame()
    for p, d in zip(params, param_datas):
        frame.param_map[p] = NDArray(d)
    _TRACE.stack.append(frame)
    _random.push_key_supply(rng_key)
    prev_train = autograd.set_training(train)
    prev_rec = autograd.set_recording(False)
    _IN_TRACE.active += 1
    try:
        result = body()
    finally:
        _IN_TRACE.active -= 1
        autograd.set_recording(prev_rec)
        autograd.set_training(prev_train)
        _random.pop_key_supply()
        _TRACE.stack.pop()
    aux = [frame.aux_updates.get(p) for p in params]
    return result, aux


# ----------------------------------------------------------------- name scope
class _BlockScope(threading.local):
    """Auto-naming of blocks/parameters (ref: gluon/block.py:_BlockScope)."""

    _current = threading.local()

    def __init__(self, block=None):
        self._block = block
        self._counter = {}
        self._old_scope = None

    @staticmethod
    def create(prefix, params, hint):
        current = getattr(_BlockScope._current, "value", None)
        if current is None:
            if prefix is None:
                count = _NameManager.next(hint)
                prefix = "%s%d_" % (hint, count)
            if params is None:
                params = ParameterDict(prefix)
            else:
                params = ParameterDict(params.prefix, params)
            return prefix, params
        if prefix is None:
            count = current._counter.get(hint, 0)
            current._counter[hint] = count + 1
            prefix = "%s%d_" % (hint, count)
        if params is None:
            parent = current._block.params
            params = ParameterDict(parent.prefix + prefix, parent._shared)
        else:
            params = ParameterDict(params.prefix, params)
        return current._block.prefix + prefix, params

    def __enter__(self):
        if self._block._empty_prefix:
            return self
        self._old_scope = getattr(_BlockScope._current, "value", None)
        _BlockScope._current.value = self
        return self

    def __exit__(self, *a):
        if self._block._empty_prefix:
            return
        _BlockScope._current.value = self._old_scope


class _NameManager:
    _lock = threading.Lock()
    _counts = {}

    @classmethod
    def next(cls, hint):
        with cls._lock:
            c = cls._counts.get(hint, 0)
            cls._counts[hint] = c + 1
            return c


# ----------------------------------------------------------------------- Block
class Block:
    """Base container for layers & models (ref: gluon/block.py:Block)."""

    def __init__(self, prefix=None, params=None):
        self._empty_prefix = prefix == ""
        self._prefix, self._params = _BlockScope.create(
            prefix, params, self._alias())
        self._name = self._prefix[:-1] if self._prefix.endswith("_") else self._prefix
        self._scope = _BlockScope(self)
        self._children = OrderedDict()
        self._reg_params = {}
        self._forward_hooks = OrderedDict()
        self._forward_pre_hooks = OrderedDict()

    def _alias(self):
        return self.__class__.__name__.lower()

    def __repr__(self):
        s = "{name}(\n{modstr}\n)" if self._children else "{name}()"
        modstr = "\n".join("  ({key}): {block}".format(
            key=k, block=_indent(repr(b), 2)) for k, b in self._children.items())
        return s.format(name=self.__class__.__name__, modstr=modstr)

    def __setattr__(self, name, value):
        if hasattr(self, name):
            existing = getattr(self, name)
            if isinstance(existing, (Parameter, Block)) and \
                    not isinstance(value, type(existing)) and \
                    not isinstance(existing, type(value)):
                raise TypeError("Changing attribute type for %s from %s to %s"
                                " is not allowed." % (name, type(existing), type(value)))
        if isinstance(value, Block):
            self.register_child(value, name)
        elif isinstance(value, Parameter):
            if hasattr(self, "_reg_params"):
                self._reg_params[name] = value
        super().__setattr__(name, value)

    @property
    def prefix(self):
        return self._prefix

    @property
    def name(self):
        return self._name

    def name_scope(self):
        """Name scope manager for child creation (ref: block.py:name_scope)."""
        return self._scope

    @property
    def params(self) -> ParameterDict:
        return self._params

    def collect_params(self, select=None) -> ParameterDict:
        """All Parameters of this block and children (ref: block.py:collect_params)."""
        ret = ParameterDict(self._params.prefix)
        if not select:
            ret.update(self.params)
        else:
            pattern = re.compile(select)
            ret.update({n: p for n, p in self.params.items() if pattern.match(n)})
        for child in self._children.values():
            ret.update(child.collect_params(select=select))
        return ret

    def register_child(self, block, name=None):
        if name is None:
            name = str(len(self._children))
        self._children[name] = block

    def register_forward_hook(self, hook):
        handle = _HookHandle(self._forward_hooks)
        self._forward_hooks[handle._id] = hook
        return handle

    def register_forward_pre_hook(self, hook):
        handle = _HookHandle(self._forward_pre_hooks)
        self._forward_pre_hooks[handle._id] = hook
        return handle

    def apply(self, fn):
        for child in self._children.values():
            child.apply(fn)
        fn(self)
        return self

    def initialize(self, init=None, ctx=None, verbose=False, force_reinit=False):
        self.collect_params().initialize(init, ctx, verbose, force_reinit)

    def hybridize(self, active=True, **kwargs):
        for child in self._children.values():
            child.hybridize(active, **kwargs)

    def cast(self, dtype):
        for child in self._children.values():
            child.cast(dtype)
        for p in self.params.values():
            p.cast(dtype)

    def save_parameters(self, filename):
        """Ref: block.py:save_parameters — strips this block's prefix so files are
        architecture-relative."""
        params = self._collect_params_with_prefix()
        from ..ndarray.utils import save as nd_save
        nd_save(filename, {k: v.data() for k, v in params.items()})

    def load_parameters(self, filename, ctx=None, allow_missing=False,
                        ignore_extra=False):
        from ..ndarray.utils import load as nd_load
        loaded = nd_load(filename)
        params = self._collect_params_with_prefix()
        if not allow_missing:
            for name in params:
                if name not in loaded:
                    raise MXNetError("Parameter %s missing in %s" % (name, filename))
        for name, v in loaded.items():
            if name not in params:
                if ignore_extra:
                    continue
                raise MXNetError("Parameter %s in file not found in Block" % name)
            params[name].set_data(v)
        return self

    # legacy aliases (ref: save_params deprecated in 1.3)
    save_params = save_parameters
    load_params = load_parameters

    def _collect_params_with_prefix(self, prefix=""):
        if prefix:
            prefix += "."
        ret = {prefix + k: v for k, v in self._reg_params.items()}
        for name, child in self._children.items():
            ret.update(child._collect_params_with_prefix(prefix + name))
        return ret

    def __call__(self, *args):
        for hook in self._forward_pre_hooks.values():
            hook(self, args)
        out = self.forward(*args)
        for hook in self._forward_hooks.values():
            hook(self, args, out)
        return out

    def forward(self, *args):  # pragma: no cover - abstract
        raise NotImplementedError

    def summary(self, *inputs):
        """Print a per-layer summary (ref: block.py:summary)."""
        rows = []

        def hook(block, inp, out):
            first = out[0] if isinstance(out, (list, tuple)) else out
            n_params = sum(p.data().size for p in block.params.values()
                           if p._data is not None)
            rows.append((block.__class__.__name__ + "-" + str(len(rows) + 1),
                         getattr(first, "shape", None), n_params))

        handles = []
        self.apply(lambda b: handles.append(b.register_forward_hook(hook)))
        try:
            self(*inputs)
        finally:
            for h in handles:
                h.detach()
        line = "%-30s %-24s %-12s"
        print(line % ("Layer (type)", "Output Shape", "Param #"))
        print("=" * 68)
        for name, shape, n in rows:
            print(line % (name, str(shape), n))
        print("=" * 68)
        total = sum(p.data().size for p in self.collect_params().values()
                    if p._data is not None)
        print("Total params: %d" % total)


class _HookHandle:
    _next_id = [0]

    def __init__(self, hooks_dict):
        self._hooks = hooks_dict
        self._id = _HookHandle._next_id[0]
        _HookHandle._next_id[0] += 1

    def detach(self):
        self._hooks.pop(self._id, None)


def _indent(s, n):
    pad = " " * n
    return ("\n" + pad).join(s.split("\n"))


# -------------------------------------------------------------------- CachedOp
class CachedOp:
    """Compiled-forward cache for a HybridBlock (ref: src/imperative/cached_op.h:83).

    One jitted executable per (input tree-structure, shapes/dtypes, train-mode) —
    jax.jit handles the shape/dtype keying; we key tree structure + mode.
    """

    def __init__(self, block):
        self._block = block
        self._params = None       # ordered list, fixed at first build
        self._aux_params = None   # params that may receive aux updates
        self._jits = {}  # (fmt_key, train, policy, shapes) -> (fwd, bwd, cell)

    def _ensure_params(self):
        if self._params is None:
            plist = [p for p in self._block.collect_params().values()]
            if any(p._data is None for p in plist):
                return False
            self._params = plist
            self._aux_params = plist  # any may push aux updates; XLA DCEs unused
        return True

    def _make_pure(self, train, cell):
        """The traced forward: one pure function over (rng, inputs,
        params) regrouping through ``cell``. Factored out so the
        companion backward can rebuild it even when the forward
        executable itself was restored from the compile service's disk
        cache (no live closure to share)."""
        block, params = self._block, self._params

        def pure(rng_key, in_datas, param_datas):
            def body():
                args, _, _ = _regroup([NDArray(d) for d in in_datas],
                                      cell["in_fmt"])
                return block._forward_eager(*args)

            out, aux = _run_traced(params, param_datas, rng_key, train, body)
            out_fmt = []
            flat_out = _flatten_nd(out, out_fmt)
            cell["out_fmt"] = out_fmt
            # output avals: the backward's cotangent example signature
            # (persisted with the entry so a disk-warm process can AOT
            # the backward without re-tracing the forward)
            cell["out_specs"] = [(tuple(o._data.shape), str(o._data.dtype))
                                 for o in flat_out]
            return [o._data for o in flat_out], aux

        return pure

    def _get_jit(self, fmt_key, train, rng_key, in_datas, param_datas):
        from .. import compile_service as csvc
        from ..ops.registry import policy_key
        policy_key_now = policy_key()
        # input shapes/dtypes join the key: the compile service may hold
        # a shape-pinned AOT executable (disk-warm start), so a new
        # input signature must be a new entry — previously jax retraced
        # internally, invisible to the watchdog
        shapes = tuple((tuple(d.shape), str(d.dtype)) for d in in_datas)
        key = (fmt_key, train, policy_key_now, shapes)
        if key in self._jits:
            return self._jits[key]
        # retrace watchdog: every CachedOp cache miss is one compile; the
        # provenance names the policy levers active at trace time, so a
        # steady-state recompile (policy env flipped mid-run, unstable
        # input signature) is attributable from telemetry.report() alone
        prov = {"block": type(self._block).__name__,
                "train": train, "policy_key": list(policy_key_now)}
        block, params = self._block, self._params
        # stable identity for the disk digest: block class + forward
        # source hash + parameter structure (an edited model across
        # restarts must miss, not replay stale code)
        struct = tuple((p.name, tuple(p._data._data.shape),
                        str(p._data._data.dtype)) for p in params)
        fn_id = "cached_op:%s:%s" % (type(block).__name__,
                                     csvc.source_token(type(block)))
        dev = csvc.device_token()
        nonce = csvc.instance_nonce(self)
        fkey = csvc.canonical_key(
            site="cached_op", fn_id=fn_id,
            signature=(fmt_key, train, shapes, struct),
            policy=policy_key_now, device=dev, nonce=nonce)

        def build():
            cell = {"in_fmt": list(fmt_key)}
            return jax.jit(self._make_pure(train, cell)), cell

        # ONE retrace count per cache miss (the fwd/bwd pair); the forward
        # executable rides compiled= into the xprof ledger and comes back
        # wrapped (compile wall-time + cost/memory analyses + call count)
        example = csvc.concrete_args((rng_key, in_datas, param_datas))
        entry = csvc.get_or_build(fkey, build, provenance=prov,
                                  example_args=example)
        jitted, cell = entry.fn, entry.meta

        def build_bwd():
            pure = self._make_pure(train, cell)

            def bwd(rng_key, in_datas, param_datas, out_cots):
                """Compiled backward: recomputes the forward inside the jit
                (remat — residuals are traded for FLOPs, the
                HBM-bandwidth-favourable choice on TPU) and applies the
                transpose. A separate executable because linearizing
                *through* a jit boundary breaks for some primitives
                (reduce_window); vjp fully inside jit is always safe."""
                n_in = len(in_datas)

                def f(*diffs):
                    outs, _aux = pure(rng_key, list(diffs[:n_in]),
                                      list(diffs[n_in:]))
                    return outs[0] if len(outs) == 1 else tuple(outs)

                _, vjp_fn = jax.vjp(f, *(list(in_datas) + list(param_datas)))
                return vjp_fn(out_cots)

            return jax.jit(bwd)

        # the companion backward shares the site's single retrace count —
        # ledger-only registration so its FLOPs still feed perf.mfu. Its
        # cotangent example comes from the forward's recorded out_specs,
        # so the backward AOT-compiles (and persists) without waiting for
        # the first autograd call — but only where a backward is
        # plausible (train mode): AOT-compiling inference backwards
        # would pay a compile nobody dispatches.
        bkey = csvc.canonical_key(
            site="cached_op", fn_id=fn_id,
            signature=("bwd", fmt_key, train, shapes, struct),
            policy=policy_key_now, device=dev, nonce=nonce)
        bwd_example = None
        if train and example is not None and cell \
                and cell.get("out_specs"):
            specs = cell["out_specs"]
            cots = [jax.ShapeDtypeStruct(s, d) for s, d in specs]
            bwd_example = example + (cots[0] if len(cots) == 1
                                     else tuple(cots),)
        bentry = csvc.get_or_build(
            bkey, build_bwd, provenance=dict(prov, kind="backward"),
            example_args=bwd_example, companion=True,
            aot=True if bwd_example is not None else None)
        self._jits[key] = (jitted, bentry.fn, cell)
        return jitted, bentry.fn, cell

    def __call__(self, *args):
        if not self._ensure_params():
            # deferred init pending: settle shapes with one eager pass
            # (gluon runs deferred shape inference on first forward too)
            out = self._block._forward_eager(*args)
            self._ensure_params()
            return out
        in_fmt = []
        flat_in = _flatten_nd(args, in_fmt)
        nd_in = [x for x in flat_in if isinstance(x, NDArray)]
        if len(nd_in) != len(flat_in):
            # static (non-NDArray) leaves present: fall back to eager
            return self._block._forward_eager(*args)
        train = autograd.is_training()
        rng_key = _random.next_key()
        in_datas = [x._data for x in nd_in]
        param_datas = [p._data._data for p in self._params]
        jitted, jitted_bwd, cell = self._get_jit(tuple(in_fmt), train,
                                                 rng_key, in_datas,
                                                 param_datas)
        cell["in_fmt"] = in_fmt

        with telemetry.span("gluon.forward"):
            out_list, aux = jitted(rng_key, in_datas, param_datas)
        out_nds = [NDArray(d) for d in out_list]

        if autograd.is_recording():
            # tape ONE node for the whole compiled forward; its vjp is the
            # companion compiled backward (CachedOp::Backward analog)
            primals_out = out_list[0] if len(out_list) == 1 else tuple(out_list)

            def vjp_fn(out_cots):
                return jitted_bwd(rng_key, in_datas, param_datas, out_cots)

            inputs = nd_in + [p._data for p in self._params]
            autograd.record_op(None, inputs, out_nds, name="CachedOp",
                               vjp=vjp_fn, primals_out=primals_out)

        for p, new in zip(self._params, aux):
            if new is not None:
                p.data()._set_data(new)
        out, _, _ = _regroup(out_nds, cell["out_fmt"])
        return out


# ------------------------------------------------------------------ HybridBlock
class HybridBlock(Block):
    """A Block whose forward can be traced & compiled (ref: block.py:HybridBlock).

    Subclasses implement ``hybrid_forward(self, F, x, *, param_name=...)`` where F
    is the op namespace (mx.nd here — under a hybrid trace the same imperative ops
    run on jax tracers, so one code path serves eager and compiled execution; the
    reference instead swaps F between mx.nd and mx.sym)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._active = False
        self._cached_op = None
        self._flags = {}

    def hybridize(self, active=True, static_alloc=False, static_shape=False,
                  **kwargs):
        """Activate compiled execution (ref: block.py:hybridize; the static_alloc /
        static_shape knobs are inherent to XLA compilation and accepted for
        compatibility)."""
        self._active = active
        self._flags = dict(static_alloc=static_alloc, static_shape=static_shape,
                           **kwargs)
        self._cached_op = None
        super().hybridize(active, static_alloc=static_alloc,
                          static_shape=static_shape, **kwargs)

    def cast(self, dtype):
        self._cached_op = None
        super().cast(dtype)

    def infer_shape(self, *args):
        """Resolve deferred parameter shapes from input shapes. Leaf layers
        override (ref: block.py:_deferred_infer_shape via symbolic inference —
        here shape propagation is per-layer and explicit)."""
        raise MXNetError(
            "Deferred initialization failed: %s cannot infer parameter shapes "
            "from inputs. Provide explicit in_units/in_channels or run "
            "a forward pass with fully-specified layers first."
            % self.__class__.__name__)

    def forward(self, *args):
        if self._active and _active_trace() is None and _IN_TRACE.active == 0:
            if self._cached_op is None:
                self._cached_op = CachedOp(self)
            return self._cached_op(*args)
        return self._forward_eager(*args)

    def _forward_eager(self, *args):
        try:
            params = {k: p.data() for k, p in self._reg_params.items()}
        except DeferredInitializationError:
            self.infer_shape(*args)
            params = {k: p.data() for k, p in self._reg_params.items()}
        # remember input signatures so export/trace can replay (symbol.py)
        self._in_specs = [(a.shape, a.dtype) for a in args
                          if isinstance(a, NDArray)]
        from .. import ndarray as F
        return self.hybrid_forward(F, *args, **params)

    def hybrid_forward(self, F, *args, **kwargs):  # pragma: no cover - abstract
        raise NotImplementedError

    def export(self, path, epoch=0):
        """Export to symbol-json + params checkpoint (ref: block.py:export).
        Requires the block to have run at least once."""
        from .. import symbol as sym_mod
        sym, arg_names = _trace_to_symbol(self)
        sym.save("%s-symbol.json" % path)
        params = self._collect_params_with_prefix()
        from ..ndarray.utils import save as nd_save
        arg = {}
        for name, p in self.collect_params().items():
            kind = "aux:" if p.grad_req == "null" else "arg:"
            arg[kind + name] = p.data()
        nd_save("%s-%04d.params" % (path, epoch), arg)
        return sym


def _trace_to_symbol(block):
    """Build a Symbol for a hybrid block by tracing with symbolic variables
    (used by export; real implementation lives in mxtpu.symbol)."""
    from ..symbol import trace_block
    return trace_block(block)


class SymbolBlock(HybridBlock):
    """Run a loaded Symbol as a Block (ref: gluon/block.py:SymbolBlock:954)."""

    def __init__(self, outputs, inputs, params=None):
        super().__init__(prefix=None, params=None)
        # param names must match the symbol's input names exactly
        # (ref: SymbolBlock.__init__ resets prefix to '')
        self._prefix = ""
        self._params = ParameterDict("", params)
        from .. import symbol as sym_mod
        if isinstance(outputs, (list, tuple)) and len(outputs) == 1:
            outputs = outputs[0]
        self._output_sym = outputs
        self._input_syms = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        input_names = {s.name for s in self._input_syms}
        # every non-input free variable becomes a Parameter
        for name in outputs.list_inputs():
            if name not in input_names:
                self.params.get(name, allow_deferred_init=True)

    @staticmethod
    def imports(symbol_file, input_names, param_file=None, ctx=None):
        from .. import symbol as sym_mod
        sym = sym_mod.load(symbol_file)
        if isinstance(input_names, str):
            input_names = [input_names]
        inputs = [sym_mod.var(n) for n in input_names]
        ret = SymbolBlock(sym, inputs)
        if param_file is not None:
            ret.collect_params().load(param_file, ctx=ctx)
        return ret

    def _forward_eager(self, *args):
        kwargs = {s.name: a for s, a in zip(self._input_syms, args)}
        for name, p in self.params.items():
            if p._data is not None:
                kwargs[name] = p.data()
        out = self._output_sym.eval(**kwargs)
        return out[0] if isinstance(out, (list, tuple)) and len(out) == 1 else out

    def hybrid_forward(self, F, *args, **kwargs):
        raise MXNetError("SymbolBlock executes its symbol directly")
