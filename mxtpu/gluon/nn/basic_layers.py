"""Basic layers: Sequential, Dense, Dropout, norms, Embedding, Flatten, Lambda
(ref: python/mxnet/gluon/nn/basic_layers.py)."""
from __future__ import annotations

from ...base import MXNetError
from ..block import Block, HybridBlock

__all__ = ["Sequential", "HybridSequential", "Dense", "Dropout", "BatchNorm",
           "InstanceNorm", "LayerNorm", "Embedding", "Flatten", "Lambda",
           "HybridLambda", "HybridConcurrent", "Concurrent", "Identity"]


class Sequential(Block):
    """Stack of Blocks run sequentially (ref: basic_layers.py:Sequential)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def forward(self, x, *args):
        for block in self._children.values():
            x = block(x, *args)
            args = ()
            if isinstance(x, (tuple, list)) and len(x) == 1:
                x = x[0]
        return x

    def __getitem__(self, key):
        layers = list(self._children.values())[key]
        if isinstance(layers, list):
            net = type(self)(prefix=self._prefix)
            net.add(*layers)
            return net
        return layers

    def __len__(self):
        return len(self._children)

    def __iter__(self):
        return iter(self._children.values())

    def hybridize(self, active=True, **kwargs):
        if self._children and all(isinstance(c, HybridBlock)
                                  for c in self._children.values()):
            import warnings
            warnings.warn("All children of this Sequential layer are "
                          "HybridBlocks. Consider using HybridSequential for "
                          "the best performance.", stacklevel=2)
        super().hybridize(active, **kwargs)


class HybridSequential(HybridBlock):
    """Hybridizable Sequential (ref: basic_layers.py:HybridSequential)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def hybrid_forward(self, F, x, *args):
        for block in self._children.values():
            x = block(x, *args)
            args = ()
            if isinstance(x, (tuple, list)) and len(x) == 1:
                x = x[0]
        return x

    def __getitem__(self, key):
        layers = list(self._children.values())[key]
        if isinstance(layers, list):
            net = type(self)(prefix=self._prefix)
            net.add(*layers)
            return net
        return layers

    def __len__(self):
        return len(self._children)

    def __iter__(self):
        return iter(self._children.values())


class Dense(HybridBlock):
    """Fully-connected layer (ref: basic_layers.py:Dense; op
    src/operator/nn/fully_connected.cc). ``flatten=True`` collapses trailing dims
    like the reference; on TPU the matmul hits the MXU whole."""

    def __init__(self, units, activation=None, use_bias=True, flatten=True,
                 dtype="float32", weight_initializer=None, bias_initializer="zeros",
                 in_units=0, **kwargs):
        super().__init__(**kwargs)
        self._flatten = flatten
        self._units = units
        self._in_units = in_units
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(units, in_units), dtype=dtype,
                init=weight_initializer, allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(units,), dtype=dtype,
                    init=bias_initializer, allow_deferred_init=True)
            else:
                self.bias = None
            if activation is not None:
                self.act = _make_activation(activation)
            else:
                self.act = None

    def infer_shape(self, x, *args):
        if self._flatten:
            in_units = 1
            for s in x.shape[1:]:
                in_units *= s
        else:
            in_units = x.shape[-1]
        self.weight._shape_resolved((self._units, in_units))
        if self.bias is not None:
            self.bias._shape_resolved((self._units,))

    def hybrid_forward(self, F, x, weight, bias=None):
        out = F.FullyConnected(x, weight, bias, num_hidden=self._units,
                               no_bias=bias is None, flatten=self._flatten)
        if self.act is not None:
            out = self.act(out)
        return out

    def __repr__(self):
        shape = self.weight.shape
        return "Dense({layout}, {act})".format(
            act=self.act if self.act else "linear",
            layout="{0} -> {1}".format(shape[1] if shape[1] else None, shape[0]))


def _make_activation(activation):
    from .activations import Activation
    if isinstance(activation, (Block,)):
        return activation
    return Activation(activation)


class Dropout(HybridBlock):
    def __init__(self, rate, axes=(), **kwargs):
        super().__init__(**kwargs)
        self._rate = rate
        self._axes = axes

    def hybrid_forward(self, F, x):
        return F.Dropout(x, p=self._rate, axes=self._axes)

    def __repr__(self):
        return "Dropout(p = {}, axes={})".format(self._rate, self._axes)


class BatchNorm(HybridBlock):
    """Batch normalization with moving stats as aux params
    (ref: basic_layers.py:BatchNorm; op src/operator/nn/batch_norm.cc).
    Under a hybrid trace the moving-stat update is collected functionally
    (Parameter._update_aux) and written back after the compiled call."""

    def __init__(self, axis=None, momentum=0.9, epsilon=1e-5, center=True, scale=True,
                 use_global_stats=False, beta_initializer="zeros",
                 gamma_initializer="ones", running_mean_initializer="zeros",
                 running_variance_initializer="ones", in_channels=0, **kwargs):
        super().__init__(**kwargs)
        if axis is None:
            # reference default is axis=1 (NCHW); under mx.layout("NHWC")
            # the channel axis moves last (mxtpu/layout.py)
            from ...layout import channel_axis
            axis = channel_axis(None)
        self._kwargs = dict(axis=axis, eps=epsilon, momentum=momentum,
                            fix_gamma=not scale, use_global_stats=use_global_stats)
        self._axis = axis
        self._momentum = momentum
        self._in_channels = in_channels
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True, differentiable=scale)
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True, differentiable=center)
            self.running_mean = self.params.get(
                "running_mean", grad_req="null", shape=(in_channels,),
                init=running_mean_initializer, allow_deferred_init=True,
                differentiable=False)
            self.running_var = self.params.get(
                "running_var", grad_req="null", shape=(in_channels,),
                init=running_variance_initializer, allow_deferred_init=True,
                differentiable=False)

    def infer_shape(self, x, *args):
        channels = x.shape[self._axis]
        for p in (self.gamma, self.beta, self.running_mean, self.running_var):
            p._shape_resolved((channels,))

    def cast(self, dtype):
        if str(dtype).startswith("float16") or str(dtype) == "bfloat16":
            dtype = "float32"  # stats in f32 (ref: BatchNorm cast override)
        super().cast(dtype)

    def hybrid_forward(self, F, x, gamma, beta, running_mean, running_var):
        from ... import autograd
        out = F.BatchNorm(x, gamma, beta, running_mean, running_var,
                          output_mean_var=autograd.is_training()
                          and not self._kwargs["use_global_stats"],
                          **self._kwargs)
        if isinstance(out, (list, tuple)):
            out, mean, var = out
            m = self._momentum
            self.running_mean._update_aux(running_mean * m + mean * (1 - m))
            self.running_var._update_aux(running_var * m + var * (1 - m))
        return out

    def __repr__(self):
        return "BatchNorm(axis={}, eps={}, momentum={}, in_channels={})".format(
            self._axis, self._kwargs["eps"], self._momentum, self.gamma.shape[0])


class InstanceNorm(HybridBlock):
    def __init__(self, axis=1, epsilon=1e-5, center=True, scale=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._epsilon = epsilon
        self._axis = axis
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True)
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True)

    def infer_shape(self, x, *args):
        channels = x.shape[self._axis]
        self.gamma._shape_resolved((channels,))
        self.beta._shape_resolved((channels,))

    def hybrid_forward(self, F, x, gamma, beta):
        if self._axis == 1:
            return F.InstanceNorm(x, gamma, beta, eps=self._epsilon)
        x = x.swapaxes(1, self._axis)
        return F.InstanceNorm(x, gamma, beta, eps=self._epsilon).swapaxes(1, self._axis)


class LayerNorm(HybridBlock):
    """Layer normalization (ref: basic_layers.py:LayerNorm; op
    src/operator/nn/layer_norm.cc)."""

    def __init__(self, axis=-1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._epsilon = epsilon
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True)
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True)

    def infer_shape(self, x, *args):
        channels = x.shape[self._axis]
        self.gamma._shape_resolved((channels,))
        self.beta._shape_resolved((channels,))

    def hybrid_forward(self, F, x, gamma, beta):
        return F.LayerNorm(x, gamma, beta, axis=self._axis, eps=self._epsilon)


class Embedding(HybridBlock):
    """Index → vector lookup (ref: basic_layers.py:Embedding; op
    src/operator/tensor/indexing_op.h). ``sparse_grad`` maps to a row-sparse
    gradient in the reference; on TPU gradients stay dense (scatter-add fuses on
    XLA) and the flag is accepted for API parity."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, sparse_grad=False, **kwargs):
        super().__init__(**kwargs)
        self._input_dim = input_dim
        self._output_dim = output_dim
        self._sparse_grad = sparse_grad
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(input_dim, output_dim), dtype=dtype,
                init=weight_initializer,
                grad_stype="row_sparse" if sparse_grad else "default")

    def hybrid_forward(self, F, x, weight):
        return F.Embedding(x, weight, input_dim=self._input_dim,
                           output_dim=self._output_dim)

    def __repr__(self):
        return "Embedding({} -> {}, {})".format(
            self._input_dim, self._output_dim, self.weight.dtype)


class Flatten(HybridBlock):
    def __init__(self, **kwargs):
        super().__init__(**kwargs)

    def hybrid_forward(self, F, x):
        return x.flatten()

    def __repr__(self):
        return "Flatten"


class Lambda(Block):
    """Wrap a function as a Block (ref: basic_layers.py:Lambda)."""

    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            from ... import ndarray as nd
            if not hasattr(nd, function):
                raise MXNetError("Function name %s is not found in mx.nd." % function)
            self._func_impl = getattr(nd, function)
            self._func_name = function
        else:
            self._func_impl = function
            self._func_name = getattr(function, "__name__", "custom")

    def forward(self, *args):
        return self._func_impl(*args)

    def __repr__(self):
        return "Lambda({})".format(self._func_name)


class HybridLambda(HybridBlock):
    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            from ... import ndarray as nd
            if not hasattr(nd, function):
                raise MXNetError("Function name %s is not found in mx.nd." % function)
            fn = getattr(nd, function)
            self._func = lambda F, *args: fn(*args)
            self._func_name = function
        else:
            self._func = function
            self._func_name = getattr(function, "__name__", "custom")

    def hybrid_forward(self, F, *args):
        return self._func(F, *args)

    def __repr__(self):
        return "HybridLambda({})".format(self._func_name)


class Concurrent(Sequential):
    """Run children on the same input, concat outputs on ``axis``
    (ref: python/mxnet/gluon/contrib/nn/basic_layers.py:Concurrent)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def forward(self, x):
        from ... import ndarray as nd
        return nd.concat(*[block(x) for block in self._children.values()],
                         dim=self.axis)


class HybridConcurrent(HybridSequential):
    """Hybridizable Concurrent (ref: contrib/nn/basic_layers.py:HybridConcurrent)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def hybrid_forward(self, F, x):
        return F.concat(*[block(x) for block in self._children.values()],
                        dim=self.axis)


class Identity(HybridBlock):
    """Identity mapping (ref: contrib/nn/basic_layers.py:Identity)."""

    def hybrid_forward(self, F, x):
        return x
