"""Convolution & pooling layers (ref: python/mxnet/gluon/nn/conv_layers.py).

Layout: the reference is channels-first only; here every layer also runs
channels-last (the TPU-native layout) — pass ``layout="NHWC"`` explicitly or
build under ``mx.layout("NHWC")`` (mxtpu/layout.py). Channels-last convs
store weights HWIO, exactly what ``lax.conv_general_dilated`` consumes with
zero relayout ops on the MXU.
"""
from __future__ import annotations

from ...base import MXNetError
from ...layout import channel_axis as _scope_channel_axis
from ...layout import conv_layout as _scope_conv_layout
from ..block import HybridBlock

__all__ = ["Conv1D", "Conv2D", "Conv3D", "Conv1DTranspose", "Conv2DTranspose",
           "Conv3DTranspose", "MaxPool1D", "MaxPool2D", "MaxPool3D", "AvgPool1D",
           "AvgPool2D", "AvgPool3D", "GlobalMaxPool1D", "GlobalMaxPool2D",
           "GlobalMaxPool3D", "GlobalAvgPool1D", "GlobalAvgPool2D",
           "GlobalAvgPool3D", "ReflectionPad2D"]


def _tuplify(v, n):
    if isinstance(v, (list, tuple)):
        return tuple(v)
    return (v,) * n


class _Conv(HybridBlock):
    """Shared conv implementation (ref: conv_layers.py:_Conv)."""

    def __init__(self, channels, kernel_size, strides, padding, dilation, groups,
                 layout, in_channels=0, activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros", op_name="Convolution",
                 adj=None, **kwargs):
        super().__init__(**kwargs)
        self._channels = channels
        self._in_channels = in_channels
        ndim = len(kernel_size)
        layout = _scope_conv_layout(layout, ndim)
        self._layout = layout
        self._channels_last = _scope_channel_axis(layout) == -1
        self._op_name = op_name
        self._kwargs = dict(kernel=kernel_size, stride=strides, dilate=dilation,
                            pad=padding, num_filter=channels, num_group=groups,
                            no_bias=not use_bias, layout=layout)
        if adj is not None:
            self._kwargs["adj"] = adj
        # weight layout: channels-first (out, in/g, *k) for Convolution /
        # (in, out/g, *k) transposed; channels-last stores what the HLO
        # consumes directly — (*k, in/g, out) / (*k, out/g, in).
        wshape = self._weight_shape(in_channels)
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=wshape, init=weight_initializer,
                allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(channels,), init=bias_initializer,
                    allow_deferred_init=True)
            else:
                self.bias = None
            if activation is not None:
                from .activations import Activation
                self.act = Activation(activation)
            else:
                self.act = None

    def _channel_axis(self):
        return _scope_channel_axis(self._layout)

    def _weight_shape(self, in_channels):
        groups = self._kwargs["num_group"]
        kernel = tuple(self._kwargs["kernel"])
        in_g = in_channels // groups if in_channels else 0
        out_g = self._channels // groups if self._channels else 0
        if self._op_name == "Convolution":
            if self._channels_last:
                return kernel + (in_g, self._channels)
            return (self._channels, in_g) + kernel
        if self._channels_last:
            return kernel + (out_g, in_channels)
        return (in_channels, out_g) + kernel

    def infer_shape(self, x, *args):
        in_c = x.shape[self._channel_axis()]
        self._in_channels = in_c
        self.weight._shape_resolved(self._weight_shape(in_c))
        if self.bias is not None:
            self.bias._shape_resolved((self._channels,))

    def hybrid_forward(self, F, x, weight, bias=None):
        op = getattr(F, self._op_name)
        act = op(x, weight, bias, **self._kwargs)
        if self.act is not None:
            act = self.act(act)
        return act

    def __repr__(self):
        s = "{name}({mapping}, kernel_size={kernel}, stride={stride})"
        return s.format(name=self.__class__.__name__,
                        mapping="{0} -> {1}".format(self._in_channels or None,
                                                    self._channels),
                        kernel=self._kwargs["kernel"], stride=self._kwargs["stride"])


class Conv1D(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0, dilation=1,
                 groups=1, layout=None, activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros", in_channels=0,
                 **kwargs):
        super().__init__(channels, _tuplify(kernel_size, 1), _tuplify(strides, 1),
                         _tuplify(padding, 1), _tuplify(dilation, 1), groups, layout,
                         in_channels, activation, use_bias, weight_initializer,
                         bias_initializer, **kwargs)


class Conv2D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 dilation=(1, 1), groups=1, layout=None, activation=None,
                 use_bias=True, weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, **kwargs):
        super().__init__(channels, _tuplify(kernel_size, 2), _tuplify(strides, 2),
                         _tuplify(padding, 2), _tuplify(dilation, 2), groups, layout,
                         in_channels, activation, use_bias, weight_initializer,
                         bias_initializer, **kwargs)


class Conv3D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1), padding=(0, 0, 0),
                 dilation=(1, 1, 1), groups=1, layout=None, activation=None,
                 use_bias=True, weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, **kwargs):
        super().__init__(channels, _tuplify(kernel_size, 3), _tuplify(strides, 3),
                         _tuplify(padding, 3), _tuplify(dilation, 3), groups, layout,
                         in_channels, activation, use_bias, weight_initializer,
                         bias_initializer, **kwargs)


class Conv1DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0, output_padding=0,
                 dilation=1, groups=1, layout=None, activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros", in_channels=0,
                 **kwargs):
        super().__init__(channels, _tuplify(kernel_size, 1), _tuplify(strides, 1),
                         _tuplify(padding, 1), _tuplify(dilation, 1), groups, layout,
                         in_channels, activation, use_bias, weight_initializer,
                         bias_initializer, op_name="Deconvolution",
                         adj=_tuplify(output_padding, 1), **kwargs)
        self.outpad = _tuplify(output_padding, 1)


class Conv2DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 output_padding=(0, 0), dilation=(1, 1), groups=1, layout=None,
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        super().__init__(channels, _tuplify(kernel_size, 2), _tuplify(strides, 2),
                         _tuplify(padding, 2), _tuplify(dilation, 2), groups, layout,
                         in_channels, activation, use_bias, weight_initializer,
                         bias_initializer, op_name="Deconvolution",
                         adj=_tuplify(output_padding, 2), **kwargs)
        self.outpad = _tuplify(output_padding, 2)


class Conv3DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1), padding=(0, 0, 0),
                 output_padding=(0, 0, 0), dilation=(1, 1, 1), groups=1,
                 layout=None, activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros", in_channels=0,
                 **kwargs):
        super().__init__(channels, _tuplify(kernel_size, 3), _tuplify(strides, 3),
                         _tuplify(padding, 3), _tuplify(dilation, 3), groups, layout,
                         in_channels, activation, use_bias, weight_initializer,
                         bias_initializer, op_name="Deconvolution",
                         adj=_tuplify(output_padding, 3), **kwargs)
        self.outpad = _tuplify(output_padding, 3)


class _Pooling(HybridBlock):
    """Shared pooling implementation (ref: conv_layers.py:_Pooling)."""

    def __init__(self, pool_size, strides, padding, ceil_mode, global_pool,
                 pool_type, layout=None, count_include_pad=None, **kwargs):
        super().__init__(**kwargs)
        if strides is None:
            strides = pool_size
        layout = _scope_conv_layout(layout, len(pool_size))
        self._kwargs = dict(
            kernel=pool_size, stride=strides, pad=padding, global_pool=global_pool,
            pool_type=pool_type, layout=layout,
            pooling_convention="full" if ceil_mode else "valid")
        if count_include_pad is not None:
            self._kwargs["count_include_pad"] = count_include_pad

    def _alias(self):
        return "pool"

    def hybrid_forward(self, F, x):
        return F.Pooling(x, **self._kwargs)

    def __repr__(self):
        return "{name}(size={kernel}, stride={stride}, padding={pad}, ceil_mode={ceil})".format(
            name=self.__class__.__name__, ceil=self._kwargs["pooling_convention"] == "full",
            **{k: self._kwargs[k] for k in ("kernel", "stride", "pad")})


class MaxPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout=None,
                 ceil_mode=False, **kwargs):
        super().__init__(_tuplify(pool_size, 1),
                         _tuplify(strides, 1) if strides is not None else None,
                         _tuplify(padding, 1), ceil_mode, False, "max", layout, **kwargs)


class MaxPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0, layout=None,
                 ceil_mode=False, **kwargs):
        super().__init__(_tuplify(pool_size, 2),
                         _tuplify(strides, 2) if strides is not None else None,
                         _tuplify(padding, 2), ceil_mode, False, "max", layout, **kwargs)


class MaxPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0, layout=None,
                 ceil_mode=False, **kwargs):
        super().__init__(_tuplify(pool_size, 3),
                         _tuplify(strides, 3) if strides is not None else None,
                         _tuplify(padding, 3), ceil_mode, False, "max", layout, **kwargs)


class AvgPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout=None,
                 ceil_mode=False, count_include_pad=True, **kwargs):
        super().__init__(_tuplify(pool_size, 1),
                         _tuplify(strides, 1) if strides is not None else None,
                         _tuplify(padding, 1), ceil_mode, False, "avg", layout,
                         count_include_pad, **kwargs)


class AvgPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0, layout=None,
                 ceil_mode=False, count_include_pad=True, **kwargs):
        super().__init__(_tuplify(pool_size, 2),
                         _tuplify(strides, 2) if strides is not None else None,
                         _tuplify(padding, 2), ceil_mode, False, "avg", layout,
                         count_include_pad, **kwargs)


class AvgPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0, layout=None,
                 ceil_mode=False, count_include_pad=True, **kwargs):
        super().__init__(_tuplify(pool_size, 3),
                         _tuplify(strides, 3) if strides is not None else None,
                         _tuplify(padding, 3), ceil_mode, False, "avg", layout,
                         count_include_pad, **kwargs)


class GlobalMaxPool1D(_Pooling):
    def __init__(self, layout=None, **kwargs):
        super().__init__((1,), None, (0,), True, True, "max", layout, **kwargs)


class GlobalMaxPool2D(_Pooling):
    def __init__(self, layout=None, **kwargs):
        super().__init__((1, 1), None, (0, 0), True, True, "max", layout, **kwargs)


class GlobalMaxPool3D(_Pooling):
    def __init__(self, layout=None, **kwargs):
        super().__init__((1, 1, 1), None, (0, 0, 0), True, True, "max", layout, **kwargs)


class GlobalAvgPool1D(_Pooling):
    def __init__(self, layout=None, **kwargs):
        super().__init__((1,), None, (0,), True, True, "avg", layout, **kwargs)


class GlobalAvgPool2D(_Pooling):
    def __init__(self, layout=None, **kwargs):
        super().__init__((1, 1), None, (0, 0), True, True, "avg", layout, **kwargs)


class GlobalAvgPool3D(_Pooling):
    def __init__(self, layout=None, **kwargs):
        super().__init__((1, 1, 1), None, (0, 0, 0), True, True, "avg", layout, **kwargs)


class ReflectionPad2D(HybridBlock):
    """Reflection padding on H/W of NCHW input (ref: nn/conv_layers.py:ReflectionPad2D,
    op src/operator/pad.cc mode='reflect')."""

    def __init__(self, padding=0, **kwargs):
        super().__init__(**kwargs)
        if isinstance(padding, int):
            padding = (0, 0, 0, 0, padding, padding, padding, padding)
        self._padding = padding

    def hybrid_forward(self, F, x):
        return F.pad(x, mode="reflect", pad_width=self._padding)
