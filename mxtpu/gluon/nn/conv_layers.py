"""Convolution & pooling layers (ref: python/mxnet/gluon/nn/conv_layers.py)."""
from __future__ import annotations

from ...base import MXNetError
from ..block import HybridBlock

__all__ = ["Conv1D", "Conv2D", "Conv3D", "Conv1DTranspose", "Conv2DTranspose",
           "Conv3DTranspose", "MaxPool1D", "MaxPool2D", "MaxPool3D", "AvgPool1D",
           "AvgPool2D", "AvgPool3D", "GlobalMaxPool1D", "GlobalMaxPool2D",
           "GlobalMaxPool3D", "GlobalAvgPool1D", "GlobalAvgPool2D",
           "GlobalAvgPool3D", "ReflectionPad2D"]


def _tuplify(v, n):
    if isinstance(v, (list, tuple)):
        return tuple(v)
    return (v,) * n


class _Conv(HybridBlock):
    """Shared conv implementation (ref: conv_layers.py:_Conv)."""

    def __init__(self, channels, kernel_size, strides, padding, dilation, groups,
                 layout, in_channels=0, activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros", op_name="Convolution",
                 adj=None, **kwargs):
        super().__init__(**kwargs)
        self._channels = channels
        self._in_channels = in_channels
        ndim = len(kernel_size)
        self._layout = layout
        self._op_name = op_name
        self._kwargs = dict(kernel=kernel_size, stride=strides, dilate=dilation,
                            pad=padding, num_filter=channels, num_group=groups,
                            no_bias=not use_bias, layout=layout)
        if adj is not None:
            self._kwargs["adj"] = adj
        # weight layout: (out, in/g, *k) for Convolution; (in, out/g, *k) transposed
        if op_name == "Convolution":
            wshape = (channels, in_channels // groups if in_channels else 0) + kernel_size
        else:
            wshape = (in_channels, channels // groups if channels else 0) + kernel_size
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=wshape, init=weight_initializer,
                allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(channels,), init=bias_initializer,
                    allow_deferred_init=True)
            else:
                self.bias = None
            if activation is not None:
                from .activations import Activation
                self.act = Activation(activation)
            else:
                self.act = None

    def _channel_axis(self):
        return len(self._layout) - 1 if self._layout.endswith("C") and \
            self._layout[1] != "C" else 1

    def infer_shape(self, x, *args):
        axis = 1 if self._layout[1] == "C" else len(self._layout) - 1
        in_c = x.shape[axis]
        groups = self._kwargs["num_group"]
        kernel = tuple(self._kwargs["kernel"])
        if self._op_name == "Convolution":
            self.weight._shape_resolved((self._channels, in_c // groups) + kernel)
        else:
            self.weight._shape_resolved((in_c, self._channels // groups) + kernel)
        if self.bias is not None:
            self.bias._shape_resolved((self._channels,))

    def hybrid_forward(self, F, x, weight, bias=None):
        op = getattr(F, self._op_name)
        act = op(x, weight, bias, **self._kwargs)
        if self.act is not None:
            act = self.act(act)
        return act

    def __repr__(self):
        s = "{name}({mapping}, kernel_size={kernel}, stride={stride})"
        shape = self.weight.shape
        return s.format(name=self.__class__.__name__,
                        mapping="{0} -> {1}".format(shape[1] if shape[1] else None,
                                                    shape[0]),
                        kernel=self._kwargs["kernel"], stride=self._kwargs["stride"])


class Conv1D(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0, dilation=1,
                 groups=1, layout="NCW", activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros", in_channels=0,
                 **kwargs):
        super().__init__(channels, _tuplify(kernel_size, 1), _tuplify(strides, 1),
                         _tuplify(padding, 1), _tuplify(dilation, 1), groups, layout,
                         in_channels, activation, use_bias, weight_initializer,
                         bias_initializer, **kwargs)


class Conv2D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 dilation=(1, 1), groups=1, layout="NCHW", activation=None,
                 use_bias=True, weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, **kwargs):
        super().__init__(channels, _tuplify(kernel_size, 2), _tuplify(strides, 2),
                         _tuplify(padding, 2), _tuplify(dilation, 2), groups, layout,
                         in_channels, activation, use_bias, weight_initializer,
                         bias_initializer, **kwargs)


class Conv3D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1), padding=(0, 0, 0),
                 dilation=(1, 1, 1), groups=1, layout="NCDHW", activation=None,
                 use_bias=True, weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, **kwargs):
        super().__init__(channels, _tuplify(kernel_size, 3), _tuplify(strides, 3),
                         _tuplify(padding, 3), _tuplify(dilation, 3), groups, layout,
                         in_channels, activation, use_bias, weight_initializer,
                         bias_initializer, **kwargs)


class Conv1DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0, output_padding=0,
                 dilation=1, groups=1, layout="NCW", activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros", in_channels=0,
                 **kwargs):
        super().__init__(channels, _tuplify(kernel_size, 1), _tuplify(strides, 1),
                         _tuplify(padding, 1), _tuplify(dilation, 1), groups, layout,
                         in_channels, activation, use_bias, weight_initializer,
                         bias_initializer, op_name="Deconvolution",
                         adj=_tuplify(output_padding, 1), **kwargs)
        self.outpad = _tuplify(output_padding, 1)


class Conv2DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 output_padding=(0, 0), dilation=(1, 1), groups=1, layout="NCHW",
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        super().__init__(channels, _tuplify(kernel_size, 2), _tuplify(strides, 2),
                         _tuplify(padding, 2), _tuplify(dilation, 2), groups, layout,
                         in_channels, activation, use_bias, weight_initializer,
                         bias_initializer, op_name="Deconvolution",
                         adj=_tuplify(output_padding, 2), **kwargs)
        self.outpad = _tuplify(output_padding, 2)


class Conv3DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1), padding=(0, 0, 0),
                 output_padding=(0, 0, 0), dilation=(1, 1, 1), groups=1,
                 layout="NCDHW", activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros", in_channels=0,
                 **kwargs):
        super().__init__(channels, _tuplify(kernel_size, 3), _tuplify(strides, 3),
                         _tuplify(padding, 3), _tuplify(dilation, 3), groups, layout,
                         in_channels, activation, use_bias, weight_initializer,
                         bias_initializer, op_name="Deconvolution",
                         adj=_tuplify(output_padding, 3), **kwargs)
        self.outpad = _tuplify(output_padding, 3)


class _Pooling(HybridBlock):
    """Shared pooling implementation (ref: conv_layers.py:_Pooling)."""

    def __init__(self, pool_size, strides, padding, ceil_mode, global_pool,
                 pool_type, layout=None, count_include_pad=None, **kwargs):
        super().__init__(**kwargs)
        if strides is None:
            strides = pool_size
        self._kwargs = dict(
            kernel=pool_size, stride=strides, pad=padding, global_pool=global_pool,
            pool_type=pool_type,
            pooling_convention="full" if ceil_mode else "valid")
        if count_include_pad is not None:
            self._kwargs["count_include_pad"] = count_include_pad

    def _alias(self):
        return "pool"

    def hybrid_forward(self, F, x):
        return F.Pooling(x, **self._kwargs)

    def __repr__(self):
        return "{name}(size={kernel}, stride={stride}, padding={pad}, ceil_mode={ceil})".format(
            name=self.__class__.__name__, ceil=self._kwargs["pooling_convention"] == "full",
            **{k: self._kwargs[k] for k in ("kernel", "stride", "pad")})


class MaxPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False, **kwargs):
        super().__init__(_tuplify(pool_size, 1),
                         _tuplify(strides, 1) if strides is not None else None,
                         _tuplify(padding, 1), ceil_mode, False, "max", layout, **kwargs)


class MaxPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0, layout="NCHW",
                 ceil_mode=False, **kwargs):
        super().__init__(_tuplify(pool_size, 2),
                         _tuplify(strides, 2) if strides is not None else None,
                         _tuplify(padding, 2), ceil_mode, False, "max", layout, **kwargs)


class MaxPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0, layout="NCDHW",
                 ceil_mode=False, **kwargs):
        super().__init__(_tuplify(pool_size, 3),
                         _tuplify(strides, 3) if strides is not None else None,
                         _tuplify(padding, 3), ceil_mode, False, "max", layout, **kwargs)


class AvgPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False, count_include_pad=True, **kwargs):
        super().__init__(_tuplify(pool_size, 1),
                         _tuplify(strides, 1) if strides is not None else None,
                         _tuplify(padding, 1), ceil_mode, False, "avg", layout,
                         count_include_pad, **kwargs)


class AvgPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0, layout="NCHW",
                 ceil_mode=False, count_include_pad=True, **kwargs):
        super().__init__(_tuplify(pool_size, 2),
                         _tuplify(strides, 2) if strides is not None else None,
                         _tuplify(padding, 2), ceil_mode, False, "avg", layout,
                         count_include_pad, **kwargs)


class AvgPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0, layout="NCDHW",
                 ceil_mode=False, count_include_pad=True, **kwargs):
        super().__init__(_tuplify(pool_size, 3),
                         _tuplify(strides, 3) if strides is not None else None,
                         _tuplify(padding, 3), ceil_mode, False, "avg", layout,
                         count_include_pad, **kwargs)


class GlobalMaxPool1D(_Pooling):
    def __init__(self, layout="NCW", **kwargs):
        super().__init__((1,), None, (0,), True, True, "max", layout, **kwargs)


class GlobalMaxPool2D(_Pooling):
    def __init__(self, layout="NCHW", **kwargs):
        super().__init__((1, 1), None, (0, 0), True, True, "max", layout, **kwargs)


class GlobalMaxPool3D(_Pooling):
    def __init__(self, layout="NCDHW", **kwargs):
        super().__init__((1, 1, 1), None, (0, 0, 0), True, True, "max", layout, **kwargs)


class GlobalAvgPool1D(_Pooling):
    def __init__(self, layout="NCW", **kwargs):
        super().__init__((1,), None, (0,), True, True, "avg", layout, **kwargs)


class GlobalAvgPool2D(_Pooling):
    def __init__(self, layout="NCHW", **kwargs):
        super().__init__((1, 1), None, (0, 0), True, True, "avg", layout, **kwargs)


class GlobalAvgPool3D(_Pooling):
    def __init__(self, layout="NCDHW", **kwargs):
        super().__init__((1, 1, 1), None, (0, 0, 0), True, True, "avg", layout, **kwargs)


class ReflectionPad2D(HybridBlock):
    """Reflection padding on H/W of NCHW input (ref: nn/conv_layers.py:ReflectionPad2D,
    op src/operator/pad.cc mode='reflect')."""

    def __init__(self, padding=0, **kwargs):
        super().__init__(**kwargs)
        if isinstance(padding, int):
            padding = (0, 0, 0, 0, padding, padding, padding, padding)
        self._padding = padding

    def hybrid_forward(self, F, x):
        return F.pad(x, mode="reflect", pad_width=self._padding)
