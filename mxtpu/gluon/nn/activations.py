"""Activation layers (ref: python/mxnet/gluon/nn/activations.py)."""
from __future__ import annotations

from ..block import HybridBlock

__all__ = ["Activation", "LeakyReLU", "PReLU", "ELU", "SELU", "Swish", "GELU"]


class Activation(HybridBlock):
    """Wraps the Activation op (ref: nn/activations.py:Activation)."""

    def __init__(self, activation, **kwargs):
        self._act_type = activation
        super().__init__(**kwargs)

    def _alias(self):
        return self._act_type

    def hybrid_forward(self, F, x):
        return F.Activation(x, act_type=self._act_type)

    def __repr__(self):
        return "Activation({})".format(self._act_type)


class LeakyReLU(HybridBlock):
    def __init__(self, alpha, **kwargs):
        super().__init__(**kwargs)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="leaky", slope=self._alpha)

    def __repr__(self):
        return "LeakyReLU({})".format(self._alpha)


class PReLU(HybridBlock):
    """Learnable leaky slope (ref: nn/activations.py:PReLU)."""

    def __init__(self, alpha_initializer=None, **kwargs):
        super().__init__(**kwargs)
        from ... import initializer as init_mod
        with self.name_scope():
            self.alpha = self.params.get(
                "alpha", shape=(1,),
                init=alpha_initializer or init_mod.Constant(0.25))

    def hybrid_forward(self, F, x, alpha):
        return F.LeakyReLU(x, gamma=alpha, act_type="prelu")


class ELU(HybridBlock):
    def __init__(self, alpha=1.0, **kwargs):
        super().__init__(**kwargs)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="elu", slope=self._alpha)


class SELU(HybridBlock):
    def __init__(self, **kwargs):
        super().__init__(**kwargs)

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="selu")


class Swish(HybridBlock):
    def __init__(self, beta=1.0, **kwargs):
        super().__init__(**kwargs)
        self._beta = beta

    def hybrid_forward(self, F, x):
        return x * F.sigmoid(self._beta * x)


class GELU(HybridBlock):
    """Gaussian Error Linear Unit (tanh approximation; transformer staple —
    no reference counterpart in 1.3, provided for the BERT model family)."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)

    def hybrid_forward(self, F, x):
        return 0.5 * x * (1.0 + F.tanh(0.7978845608028654 * (x + 0.044715 * x * x * x)))
