"""Contrib nn layers (ref: python/mxnet/gluon/contrib/nn/basic_layers.py)."""
from .basic_layers import (Concurrent, HybridConcurrent, Identity,
                           SparseEmbedding, SwitchMoE, SyncBatchNorm)

__all__ = ["Concurrent", "HybridConcurrent", "Identity", "SparseEmbedding",
           "SyncBatchNorm", "SwitchMoE"]
