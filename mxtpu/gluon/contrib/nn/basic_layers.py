"""Contrib layers (ref: python/mxnet/gluon/contrib/nn/basic_layers.py)."""
from __future__ import annotations

from ...block import HybridBlock
from ...nn import BatchNorm, HybridSequential, Embedding

__all__ = ["Concurrent", "HybridConcurrent", "Identity", "SparseEmbedding",
           "SyncBatchNorm", "SwitchMoE"]


class Concurrent(HybridSequential):
    """Run children on the same input, concat outputs (ref: Concurrent)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def hybrid_forward(self, F, x):
        out = [block(x) for block in self._children.values()]
        return F.concat(*out, dim=self.axis)


class HybridConcurrent(Concurrent):
    pass


class Identity(HybridBlock):
    def hybrid_forward(self, F, x):
        return x


class SparseEmbedding(HybridBlock):
    """Embedding backed by row_sparse gradients (ref: SparseEmbedding).

    TPU note: gradients stay dense under jit (XLA scatter-add); the
    row_sparse benefit of the reference (PS bandwidth) is subsumed by the
    collective data plane, so this is API parity over the same Embedding op.
    """

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, **kwargs):
        super().__init__(**kwargs)
        self._kwargs = {"input_dim": input_dim, "output_dim": output_dim,
                        "dtype": dtype, "sparse_grad": True}
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(input_dim, output_dim), dtype=dtype,
                init=weight_initializer, grad_stype="row_sparse")

    def hybrid_forward(self, F, x, weight):
        return F.Embedding(x, weight, **{k: v for k, v in self._kwargs.items()
                                         if k != "sparse_grad"})

    def __repr__(self):
        return "SparseEmbedding({input_dim} -> {output_dim}, {dtype})".format(
            **self._kwargs)


class SyncBatchNorm(BatchNorm):
    """Cross-device synchronized BatchNorm (ref: contrib SyncBatchNorm over
    src/operator/contrib/sync_batch_norm.cc — a barrier/broadcast protocol
    across GPU workers).

    TPU-native: inside a jitted sharded step, batch statistics are GLOBAL
    means over the full (mesh-sharded) batch automatically — GSPMD inserts the
    cross-replica reduction, so plain BatchNorm *is* SyncBatchNorm on the
    mesh. Kept as a distinct class for API parity; `num_devices` is accepted
    and ignored.
    """

    def __init__(self, in_channels=0, num_devices=None, momentum=0.9,
                 epsilon=1e-5, center=True, scale=True, use_global_stats=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 running_mean_initializer="zeros",
                 running_variance_initializer="ones", **kwargs):
        super().__init__(axis=1, momentum=momentum, epsilon=epsilon,
                         center=center, scale=scale,
                         use_global_stats=use_global_stats,
                         beta_initializer=beta_initializer,
                         gamma_initializer=gamma_initializer,
                         running_mean_initializer=running_mean_initializer,
                         running_variance_initializer=running_variance_initializer,
                         in_channels=in_channels, **kwargs)


class SwitchMoE(HybridBlock):
    """Top-1 switch mixture-of-experts FFN layer (no reference counterpart
    — SURVEY §2.3 lists MoE/expert parallelism as absent upstream).

    Wraps the registered ``_contrib_switch_moe`` op (mxtpu.parallel.moe
    switch_ffn): router + E expert FFNs as dispatch/combine einsums so
    GSPMD lowers routing to all-to-all when the expert weights live on an
    ``expert`` mesh axis (place them with ``mxtpu.parallel.shard_experts``
    or ShardedTrainStep param_specs).

    Returns ``(out, aux_loss)`` — the Switch load-balancing loss is a REAL
    second output (not a side-channel attribute), so it survives
    hybridize()/export and its gradient flows when added to the objective.

    Input (..., dim) is flattened to tokens and restored, so the layer
    drops into transformer blocks shaped (batch, seq, dim).
    """

    def __init__(self, dim, hidden, num_experts, capacity_factor=1.25,
                 **kwargs):
        super().__init__(**kwargs)
        self._dim, self._hidden = dim, hidden
        self._num_experts = num_experts
        self._capacity_factor = capacity_factor
        with self.name_scope():
            self.router = self.params.get("router", shape=(dim, num_experts))
            self.w1 = self.params.get("w1", shape=(num_experts, dim, hidden))
            self.b1 = self.params.get("b1", shape=(num_experts, hidden),
                                      init="zeros")
            self.w2 = self.params.get("w2", shape=(num_experts, hidden, dim))
            self.b2 = self.params.get("b2", shape=(num_experts, dim),
                                      init="zeros")

    def hybrid_forward(self, F, x, router, w1, b1, w2, b2):
        if x.shape[-1] != self._dim:
            raise ValueError(
                "SwitchMoE(dim=%d) got input with last axis %d"
                % (self._dim, x.shape[-1]))
        return F._contrib_switch_moe(x, router, w1, b1, w2, b2,
                                     capacity_factor=self._capacity_factor)

    def __repr__(self):
        return "SwitchMoE(dim=%d, hidden=%d, experts=%d)" % (
            self._dim, self._hidden, self._num_experts)
