"""Contrib layers (ref: python/mxnet/gluon/contrib/nn/basic_layers.py)."""
from __future__ import annotations

from ...block import HybridBlock
from ...nn import BatchNorm, HybridSequential, Embedding

__all__ = ["Concurrent", "HybridConcurrent", "Identity", "SparseEmbedding",
           "SyncBatchNorm"]


class Concurrent(HybridSequential):
    """Run children on the same input, concat outputs (ref: Concurrent)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def hybrid_forward(self, F, x):
        out = [block(x) for block in self._children.values()]
        return F.concat(*out, dim=self.axis)


class HybridConcurrent(Concurrent):
    pass


class Identity(HybridBlock):
    def hybrid_forward(self, F, x):
        return x


class SparseEmbedding(HybridBlock):
    """Embedding backed by row_sparse gradients (ref: SparseEmbedding).

    TPU note: gradients stay dense under jit (XLA scatter-add); the
    row_sparse benefit of the reference (PS bandwidth) is subsumed by the
    collective data plane, so this is API parity over the same Embedding op.
    """

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, **kwargs):
        super().__init__(**kwargs)
        self._kwargs = {"input_dim": input_dim, "output_dim": output_dim,
                        "dtype": dtype, "sparse_grad": True}
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(input_dim, output_dim), dtype=dtype,
                init=weight_initializer, grad_stype="row_sparse")

    def hybrid_forward(self, F, x, weight):
        return F.Embedding(x, weight, **{k: v for k, v in self._kwargs.items()
                                         if k != "sparse_grad"})

    def __repr__(self):
        return "SparseEmbedding({input_dim} -> {output_dim}, {dtype})".format(
            **self._kwargs)


class SyncBatchNorm(BatchNorm):
    """Cross-device synchronized BatchNorm (ref: contrib SyncBatchNorm over
    src/operator/contrib/sync_batch_norm.cc — a barrier/broadcast protocol
    across GPU workers).

    TPU-native: inside a jitted sharded step, batch statistics are GLOBAL
    means over the full (mesh-sharded) batch automatically — GSPMD inserts the
    cross-replica reduction, so plain BatchNorm *is* SyncBatchNorm on the
    mesh. Kept as a distinct class for API parity; `num_devices` is accepted
    and ignored.
    """

    def __init__(self, in_channels=0, num_devices=None, momentum=0.9,
                 epsilon=1e-5, center=True, scale=True, use_global_stats=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 running_mean_initializer="zeros",
                 running_variance_initializer="ones", **kwargs):
        super().__init__(axis=1, momentum=momentum, epsilon=epsilon,
                         center=center, scale=scale,
                         use_global_stats=use_global_stats,
                         beta_initializer=beta_initializer,
                         gamma_initializer=gamma_initializer,
                         running_mean_initializer=running_mean_initializer,
                         running_variance_initializer=running_variance_initializer,
                         in_channels=in_channels, **kwargs)
