"""Convolutional RNN cells (ref: python/mxnet/gluon/contrib/rnn/conv_rnn_cell.py).

State and input are feature maps; i2h/h2h are convolutions instead of dense
projections. Gate packing matches the dense cells (LSTM i,f,g,o; GRU r,z,n).
"""
from __future__ import annotations

from ....base import MXNetError
from ...rnn.rnn_cell import HybridRecurrentCell

__all__ = ["Conv1DRNNCell", "Conv2DRNNCell", "Conv3DRNNCell",
           "Conv1DLSTMCell", "Conv2DLSTMCell", "Conv3DLSTMCell",
           "Conv1DGRUCell", "Conv2DGRUCell", "Conv3DGRUCell"]


def _tuplify(v, n):
    return (v,) * n if isinstance(v, int) else tuple(v)


class _BaseConvRNNCell(HybridRecurrentCell):
    def __init__(self, input_shape, hidden_channels, i2h_kernel, h2h_kernel,
                 i2h_pad, i2h_dilate, h2h_dilate, i2h_weight_initializer,
                 h2h_weight_initializer, i2h_bias_initializer,
                 h2h_bias_initializer, dims, conv_layout, activation,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_channels = hidden_channels
        self._input_shape = tuple(input_shape)
        self._conv_layout = conv_layout
        self._activation = activation
        self._dims = dims
        self._i2h_kernel = _tuplify(i2h_kernel, dims)
        self._h2h_kernel = _tuplify(h2h_kernel, dims)
        for k in self._h2h_kernel:
            if k % 2 == 0:
                raise MXNetError(
                    "h2h_kernel must be odd so the state shape is preserved; "
                    "got %s" % (self._h2h_kernel,))
        self._i2h_pad = _tuplify(i2h_pad, dims)
        self._i2h_dilate = _tuplify(i2h_dilate, dims)
        self._h2h_dilate = _tuplify(h2h_dilate, dims)
        # SAME padding for h2h
        self._h2h_pad = tuple(d * (k - 1) // 2 for d, k in
                              zip(self._h2h_dilate, self._h2h_kernel))
        in_channels = self._input_shape[0]
        num_gates = self._num_gates
        self._state_shape = self._compute_state_shape()
        self.i2h_weight = self.params.get(
            "i2h_weight",
            shape=(hidden_channels * num_gates, in_channels) + self._i2h_kernel,
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight",
            shape=(hidden_channels * num_gates, hidden_channels)
            + self._h2h_kernel,
            init=h2h_weight_initializer, allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(hidden_channels * num_gates,),
            init=i2h_bias_initializer, allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(hidden_channels * num_gates,),
            init=h2h_bias_initializer, allow_deferred_init=True)

    def _compute_state_shape(self):
        spatial = self._input_shape[1:]
        out_spatial = []
        for s, k, p, d in zip(spatial, self._i2h_kernel, self._i2h_pad,
                              self._i2h_dilate):
            out_spatial.append((s + 2 * p - d * (k - 1) - 1) + 1)
        return (self._hidden_channels,) + tuple(out_spatial)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size,) + self._state_shape,
                 "__layout__": self._conv_layout}] * self._num_states

    def infer_shape(self, inputs, states):
        pass  # shapes are explicit via input_shape

    def _conv(self, F, x, weight, bias, pad, dilate):
        return F.Convolution(
            x, weight, bias, kernel=weight.shape[2:],
            stride=(1,) * self._dims, dilate=dilate, pad=pad,
            num_filter=weight.shape[0])

    def _gates(self, F, inputs, states, i2h_weight, h2h_weight, i2h_bias,
               h2h_bias):
        i2h = self._conv(F, inputs, i2h_weight, i2h_bias, self._i2h_pad,
                         self._i2h_dilate)
        h2h = self._conv(F, states[0], h2h_weight, h2h_bias, self._h2h_pad,
                         self._h2h_dilate)
        return i2h, h2h


class _ConvRNNCell(_BaseConvRNNCell):
    _num_gates = 1
    _num_states = 1

    def _alias(self):
        return "conv_rnn"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h, h2h = self._gates(F, inputs, states, i2h_weight, h2h_weight,
                               i2h_bias, h2h_bias)
        output = self._get_activation(F, i2h + h2h, self._activation)
        return output, [output]


class _ConvLSTMCell(_BaseConvRNNCell):
    _num_gates = 4
    _num_states = 2

    def _alias(self):
        return "conv_lstm"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h, h2h = self._gates(F, inputs, states, i2h_weight, h2h_weight,
                               i2h_bias, h2h_bias)
        gates = i2h + h2h
        slices = F.SliceChannel(gates, num_outputs=4, axis=1)
        in_gate = F.sigmoid(slices[0])
        forget_gate = F.sigmoid(slices[1])
        in_transform = self._get_activation(F, slices[2], self._activation)
        out_gate = F.sigmoid(slices[3])
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * self._get_activation(F, next_c, self._activation)
        return next_h, [next_h, next_c]


class _ConvGRUCell(_BaseConvRNNCell):
    _num_gates = 3
    _num_states = 1

    def _alias(self):
        return "conv_gru"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h, h2h = self._gates(F, inputs, states, i2h_weight, h2h_weight,
                               i2h_bias, h2h_bias)
        i2h_s = F.SliceChannel(i2h, num_outputs=3, axis=1)
        h2h_s = F.SliceChannel(h2h, num_outputs=3, axis=1)
        reset_gate = F.sigmoid(i2h_s[0] + h2h_s[0])
        update_gate = F.sigmoid(i2h_s[1] + h2h_s[1])
        next_h_tmp = self._get_activation(
            F, i2h_s[2] + reset_gate * h2h_s[2], self._activation)
        next_h = (1.0 - update_gate) * next_h_tmp + update_gate * states[0]
        return next_h, [next_h]


def _make(base, dims, name):
    def __init__(self, input_shape, hidden_channels, i2h_kernel=3,
                 h2h_kernel=3, i2h_pad=0, i2h_dilate=1, h2h_dilate=1,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 conv_layout=None, activation="tanh", prefix=None,
                 params=None):
        layouts = {1: "NCW", 2: "NCHW", 3: "NCDHW"}
        if activation == "leaky":
            # the reference maps 'leaky' to a LeakyReLU block
            from ...nn import LeakyReLU
            activation = LeakyReLU(alpha=0.01)
        base.__init__(self, input_shape, hidden_channels, i2h_kernel,
                      h2h_kernel, i2h_pad, i2h_dilate, h2h_dilate,
                      i2h_weight_initializer, h2h_weight_initializer,
                      i2h_bias_initializer, h2h_bias_initializer, dims,
                      conv_layout or layouts[dims], activation,
                      prefix=prefix, params=params)
    cls = type(name, (base,), {"__init__": __init__})
    cls.__doc__ = "%s (ref: contrib/rnn/conv_rnn_cell.py:%s)" % (name, name)
    return cls


Conv1DRNNCell = _make(_ConvRNNCell, 1, "Conv1DRNNCell")
Conv2DRNNCell = _make(_ConvRNNCell, 2, "Conv2DRNNCell")
Conv3DRNNCell = _make(_ConvRNNCell, 3, "Conv3DRNNCell")
Conv1DLSTMCell = _make(_ConvLSTMCell, 1, "Conv1DLSTMCell")
Conv2DLSTMCell = _make(_ConvLSTMCell, 2, "Conv2DLSTMCell")
Conv3DLSTMCell = _make(_ConvLSTMCell, 3, "Conv3DLSTMCell")
Conv1DGRUCell = _make(_ConvGRUCell, 1, "Conv1DGRUCell")
Conv2DGRUCell = _make(_ConvGRUCell, 2, "Conv2DGRUCell")
Conv3DGRUCell = _make(_ConvGRUCell, 3, "Conv3DGRUCell")
