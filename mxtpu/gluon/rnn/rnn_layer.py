"""Fused recurrent layers RNN/LSTM/GRU (ref: python/mxnet/gluon/rnn/rnn_layer.py).

These call the fused ``RNN`` op (mxtpu/ops/rnn_ops.py — one lax.scan per
layer/direction, the XLA equivalent of the reference's rnn_impl.h / cuDNN fused
kernels). Per-layer parameters use the reference's naming ({l,r}{i}_{i2h,h2h}_*)
and are packed into the flat vector layout the fused op expects at forward time.
"""
from __future__ import annotations

from ...base import MXNetError
from ..block import HybridBlock

__all__ = ["RNN", "LSTM", "GRU"]


class _RNNLayer(HybridBlock):
    def __init__(self, hidden_size, num_layers, layout, dropout, bidirectional,
                 input_size, i2h_weight_initializer, h2h_weight_initializer,
                 i2h_bias_initializer, h2h_bias_initializer, mode, **kwargs):
        super().__init__(**kwargs)
        if layout not in ("TNC", "NTC"):
            raise MXNetError("Invalid layout %s; must be TNC or NTC" % layout)
        self._hidden_size = hidden_size
        self._num_layers = num_layers
        self._mode = mode
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        self._i2h_weight_initializer = i2h_weight_initializer
        self._h2h_weight_initializer = h2h_weight_initializer
        self._i2h_bias_initializer = i2h_bias_initializer
        self._h2h_bias_initializer = h2h_bias_initializer

        self._gates = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}[mode]
        ng, ni, nh = self._gates, input_size, hidden_size
        for i in range(num_layers):
            for j in ["l", "r"][:self._dir]:
                self._register_param("{}{}_i2h_weight".format(j, i),
                                     (ng * nh, ni), i2h_weight_initializer)
                self._register_param("{}{}_h2h_weight".format(j, i),
                                     (ng * nh, nh), h2h_weight_initializer)
                self._register_param("{}{}_i2h_bias".format(j, i),
                                     (ng * nh,), i2h_bias_initializer)
                self._register_param("{}{}_h2h_bias".format(j, i),
                                     (ng * nh,), h2h_bias_initializer)
            ni = nh * self._dir

    def _register_param(self, name, shape, init):
        p = self.params.get(name, shape=shape, init=init, allow_deferred_init=True)
        self._reg_params[name] = p
        setattr(self, name, p)

    def __repr__(self):
        s = "{name}({mapping}, {_layout}"
        if self._num_layers != 1:
            s += ", num_layers={_num_layers}"
        if self._dropout != 0:
            s += ", dropout={_dropout}"
        if self._dir == 2:
            s += ", bidirectional"
        s += ")"
        shape = self.l0_i2h_weight.shape
        mapping = "{0} -> {1}".format(shape[1] if shape[1] else None,
                                      shape[0] // self._gates)
        return s.format(name=self.__class__.__name__, mapping=mapping,
                        **self.__dict__)

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def infer_shape(self, inputs, *args):
        ni = inputs.shape[2] if self._layout == "TNC" else inputs.shape[2]
        ng, nh = self._gates, self._hidden_size
        for i in range(self._num_layers):
            for j in ["l", "r"][:self._dir]:
                getattr(self, "{}{}_i2h_weight".format(j, i))._shape_resolved(
                    (ng * nh, ni))
            ni = nh * self._dir

    def begin_state(self, batch_size=0, func=None, **kwargs):
        """Initial recurrent states (ref: rnn_layer.py:begin_state)."""
        from ... import ndarray as F
        if func is None:
            func = F.zeros
        states = []
        for i, info in enumerate(self.state_info(batch_size)):
            kw = dict(kwargs)
            if info is not None:
                kw.update(info)
            states.append(func(name="%sh0_%d" % (self.prefix, i), **kw))
        return states

    def hybrid_forward(self, F, inputs, states=None, **params):
        if self._layout == "NTC":
            inputs = F.swapaxes(inputs, 0, 1)
        batch_size = inputs.shape[1]
        skip_states = states is None
        if skip_states:
            states = self.begin_state(batch_size)
        if not isinstance(states, (list, tuple)):
            states = [states]

        # pack params into the fused-op layout: weights (layer-major, dir-major,
        # i2h then h2h) then biases — matches ops/rnn_ops._unpack_params
        flat = []
        for i in range(self._num_layers):
            for j in ["l", "r"][:self._dir]:
                flat.append(params["{}{}_i2h_weight".format(j, i)].reshape(-1))
                flat.append(params["{}{}_h2h_weight".format(j, i)].reshape(-1))
        for i in range(self._num_layers):
            for j in ["l", "r"][:self._dir]:
                flat.append(params["{}{}_i2h_bias".format(j, i)])
                flat.append(params["{}{}_h2h_bias".format(j, i)])
        packed = F.concat(*flat, dim=0)

        rnn_args = dict(state_size=self._hidden_size, num_layers=self._num_layers,
                        bidirectional=self._dir == 2, p=self._dropout,
                        state_outputs=True, mode=self._mode)
        if self._mode == "lstm":
            out = F.RNN(inputs, packed, states[0], states[1], **rnn_args)
            outputs, states = out[0], [out[1], out[2]]
        else:
            out = F.RNN(inputs, packed, states[0], **rnn_args)
            outputs, states = out[0], [out[1]]
        if self._layout == "NTC":
            outputs = F.swapaxes(outputs, 0, 1)
        if skip_states:
            return outputs
        return outputs, states

    def forward(self, inputs, states=None):
        if states is None:
            return super().forward(inputs)
        return super().forward(inputs, states)


class RNN(_RNNLayer):
    """Elman RNN with tanh/relu (ref: rnn_layer.py:RNN; op src/operator/rnn.cc)."""

    def __init__(self, hidden_size, num_layers=1, activation="relu", layout="TNC",
                 dropout=0, bidirectional=False, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout, bidirectional,
                         input_size, i2h_weight_initializer, h2h_weight_initializer,
                         i2h_bias_initializer, h2h_bias_initializer,
                         "rnn_" + activation, **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]


class LSTM(_RNNLayer):
    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout, bidirectional,
                         input_size, i2h_weight_initializer, h2h_weight_initializer,
                         i2h_bias_initializer, h2h_bias_initializer, "lstm", **kwargs)

    def state_info(self, batch_size=0):
        shape = (self._num_layers * self._dir, batch_size, self._hidden_size)
        return [{"shape": shape, "__layout__": "LNC"},
                {"shape": shape, "__layout__": "LNC"}]


class GRU(_RNNLayer):
    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout, bidirectional,
                         input_size, i2h_weight_initializer, h2h_weight_initializer,
                         i2h_bias_initializer, h2h_bias_initializer, "gru", **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]
