"""Recurrent cells (ref: python/mxnet/gluon/rnn/rnn_cell.py).

Cells are per-step HybridBlocks; ``unroll`` replays them over time. Under
``hybridize()`` the unrolled python loop is traced once and compiled — XLA then
schedules it like the fused layer path, so the reference's distinction between
"slow flexible cells" and "fast fused layers" narrows to trace length.
"""
from __future__ import annotations

from ...base import MXNetError
from ..block import HybridBlock


__all__ = ["RecurrentCell", "HybridRecurrentCell", "RNNCell", "LSTMCell", "GRUCell",
           "SequentialRNNCell", "DropoutCell", "ModifierCell", "ZoneoutCell",
           "ResidualCell", "BidirectionalCell"]


def _cells_state_info(cells, batch_size):
    return sum([c.state_info(batch_size) for c in cells], [])


def _cells_begin_state(cells, **kwargs):
    return sum([c.begin_state(**kwargs) for c in cells], [])


def _format_sequence(length, inputs, layout, merge):
    """Normalize inputs to a list of per-step tensors or a merged tensor
    (ref: rnn_cell.py:_format_sequence)."""
    from ... import ndarray as F
    axis = layout.find("T")
    batch_axis = layout.find("N")
    if isinstance(inputs, (list, tuple)):
        in_axis = 0
        batch_size = inputs[0].shape[batch_axis - (1 if batch_axis > axis else 0)] \
            if inputs[0].ndim >= 2 else inputs[0].shape[0]
        if merge:
            merged = F.stack(*inputs, axis=axis)
            return merged, axis, batch_size
        return list(inputs), axis, batch_size
    batch_size = inputs.shape[batch_axis]
    if merge is False:
        seq = [F.squeeze(s, axis=axis) for s in
               F.SliceChannel(inputs, num_outputs=inputs.shape[axis], axis=axis,
                              squeeze_axis=False)]
        return seq, axis, batch_size
    return inputs, axis, batch_size


class RecurrentCell(HybridBlock):
    """Abstract cell (ref: rnn_cell.py:RecurrentCell)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1
        for cell in self._children.values():
            if isinstance(cell, RecurrentCell):
                cell.reset()

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):
        if self._modified:
            raise MXNetError("After applying modifier cells the base cell cannot "
                             "be called directly. Call the modifier cell instead.")
        from ... import ndarray as F
        if func is None:
            func = F.zeros
        states = []
        for info in self.state_info(batch_size):
            self._init_counter += 1
            kw = dict(kwargs)
            if info is not None:
                kw.update(info)
            states.append(func(name="%sbegin_state_%d" % (self._prefix,
                                                          self._init_counter), **kw))
        return states

    def __call__(self, inputs, states):
        self._counter += 1
        return super().__call__(inputs, states)

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        """Unroll over time (ref: rnn_cell.py:unroll)."""
        from ... import ndarray as F
        self.reset()
        inputs, axis, batch_size = _format_sequence(length, inputs, layout, False)
        if begin_state is None:
            begin_state = self.begin_state(batch_size=batch_size)
        states = begin_state
        outputs = []
        all_states = []
        for i in range(length):
            output, states = self(inputs[i], states)
            outputs.append(output)
            if valid_length is not None:
                all_states.append(states)
        if valid_length is not None:
            states = [F.SequenceLast(F.stack(*ele_list, axis=0),
                                     sequence_length=valid_length,
                                     use_sequence_length=True, axis=0)
                      for ele_list in zip(*all_states)]
            outputs = [
                F.where(F.broadcast_lesser(
                    F.full((batch_size,), i, dtype="float32"), valid_length),
                    outputs[i], F.zeros_like(outputs[i]))
                for i in range(length)]
        if merge_outputs:
            outputs = F.stack(*outputs, axis=axis)
        return outputs, states

    def _get_activation(self, F, inputs, activation, **kwargs):
        if isinstance(activation, str):
            return F.Activation(inputs, act_type=activation, **kwargs)
        return activation(inputs)

    def forward(self, inputs, states):
        return super().forward(inputs, states)


class HybridRecurrentCell(RecurrentCell):
    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError


class RNNCell(HybridRecurrentCell):
    """Elman cell (ref: rnn_cell.py:RNNCell)."""

    def __init__(self, hidden_size, activation="tanh", i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._activation = activation
        self._input_size = input_size
        self.i2h_weight = self.params.get("i2h_weight", shape=(hidden_size, input_size),
                                          init=i2h_weight_initializer,
                                          allow_deferred_init=True)
        self.h2h_weight = self.params.get("h2h_weight", shape=(hidden_size, hidden_size),
                                          init=h2h_weight_initializer,
                                          allow_deferred_init=True)
        self.i2h_bias = self.params.get("i2h_bias", shape=(hidden_size,),
                                        init=i2h_bias_initializer,
                                        allow_deferred_init=True)
        self.h2h_bias = self.params.get("h2h_bias", shape=(hidden_size,),
                                        init=h2h_bias_initializer,
                                        allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _alias(self):
        return "rnn"

    def infer_shape(self, inputs, states):
        self.i2h_weight._shape_resolved((self._hidden_size, inputs.shape[-1]))

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=self._hidden_size)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=self._hidden_size)
        output = self._get_activation(F, i2h + h2h, self._activation)
        return output, [output]


class LSTMCell(HybridRecurrentCell):
    """LSTM cell (ref: rnn_cell.py:LSTMCell; gate order i,f,g,o matches the
    fused op's packing)."""

    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        self.i2h_weight = self.params.get("i2h_weight",
                                          shape=(4 * hidden_size, input_size),
                                          init=i2h_weight_initializer,
                                          allow_deferred_init=True)
        self.h2h_weight = self.params.get("h2h_weight",
                                          shape=(4 * hidden_size, hidden_size),
                                          init=h2h_weight_initializer,
                                          allow_deferred_init=True)
        self.i2h_bias = self.params.get("i2h_bias", shape=(4 * hidden_size,),
                                        init=i2h_bias_initializer,
                                        allow_deferred_init=True)
        self.h2h_bias = self.params.get("h2h_bias", shape=(4 * hidden_size,),
                                        init=h2h_bias_initializer,
                                        allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _alias(self):
        return "lstm"

    def infer_shape(self, inputs, states):
        self.i2h_weight._shape_resolved((4 * self._hidden_size, inputs.shape[-1]))

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=4 * self._hidden_size)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=4 * self._hidden_size)
        gates = i2h + h2h
        slices = F.SliceChannel(gates, num_outputs=4, axis=-1)
        in_gate = F.sigmoid(slices[0])
        forget_gate = F.sigmoid(slices[1])
        in_transform = F.tanh(slices[2])
        out_gate = F.sigmoid(slices[3])
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * F.tanh(next_c)
        return next_h, [next_h, next_c]


class GRUCell(HybridRecurrentCell):
    """GRU cell (ref: rnn_cell.py:GRUCell; gate order r,z,n matches fused op)."""

    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        self.i2h_weight = self.params.get("i2h_weight",
                                          shape=(3 * hidden_size, input_size),
                                          init=i2h_weight_initializer,
                                          allow_deferred_init=True)
        self.h2h_weight = self.params.get("h2h_weight",
                                          shape=(3 * hidden_size, hidden_size),
                                          init=h2h_weight_initializer,
                                          allow_deferred_init=True)
        self.i2h_bias = self.params.get("i2h_bias", shape=(3 * hidden_size,),
                                        init=i2h_bias_initializer,
                                        allow_deferred_init=True)
        self.h2h_bias = self.params.get("h2h_bias", shape=(3 * hidden_size,),
                                        init=h2h_bias_initializer,
                                        allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _alias(self):
        return "gru"

    def infer_shape(self, inputs, states):
        self.i2h_weight._shape_resolved((3 * self._hidden_size, inputs.shape[-1]))

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        prev_h = states[0]
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=3 * self._hidden_size)
        h2h = F.FullyConnected(prev_h, h2h_weight, h2h_bias,
                               num_hidden=3 * self._hidden_size)
        i2h_r, i2h_z, i2h_n = F.SliceChannel(i2h, num_outputs=3, axis=-1)
        h2h_r, h2h_z, h2h_n = F.SliceChannel(h2h, num_outputs=3, axis=-1)
        reset_gate = F.sigmoid(i2h_r + h2h_r)
        update_gate = F.sigmoid(i2h_z + h2h_z)
        next_h_tmp = F.tanh(i2h_n + reset_gate * h2h_n)
        next_h = (1.0 - update_gate) * next_h_tmp + update_gate * prev_h
        return next_h, [next_h]


class SequentialRNNCell(RecurrentCell):
    """Stack cells (ref: rnn_cell.py:SequentialRNNCell)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, cell):
        self.register_child(cell)

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children.values(), batch_size)

    def begin_state(self, **kwargs):
        return _cells_begin_state(self._children.values(), **kwargs)

    def __call__(self, inputs, states):
        self._counter += 1
        next_states = []
        p = 0
        for cell in self._children.values():
            n = len(cell.state_info())
            state = states[p:p + n]
            p += n
            inputs, state = cell(inputs, state)
            next_states.extend(state)
        return inputs, next_states

    def __len__(self):
        return len(self._children)

    def __getitem__(self, i):
        return list(self._children.values())[i]

    def forward(self, *args):
        raise NotImplementedError

    def hybrid_forward(self, F, *args):
        raise NotImplementedError


class DropoutCell(HybridRecurrentCell):
    def __init__(self, rate, axes=(), prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._rate = rate
        self._axes = axes

    def state_info(self, batch_size=0):
        return []

    def _alias(self):
        return "dropout"

    def hybrid_forward(self, F, inputs, states):
        if self._rate > 0:
            inputs = F.Dropout(inputs, p=self._rate, axes=self._axes)
        return inputs, states


class ModifierCell(HybridRecurrentCell):
    """Base for cells wrapping another cell (ref: rnn_cell.py:ModifierCell)."""

    def __init__(self, base_cell):
        assert not base_cell._modified, \
            "Cell %s is already modified." % base_cell.name
        base_cell._modified = True
        super().__init__(prefix=base_cell.prefix + self._alias(),
                         params=None)
        self.base_cell = base_cell

    @property
    def params(self):
        return self.base_cell.params

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, func=None, **kwargs):
        assert not self._modified
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(func=func, **kwargs)
        self.base_cell._modified = True
        return begin


class ZoneoutCell(ModifierCell):
    """Zoneout regularization (ref: rnn_cell.py:ZoneoutCell)."""

    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        assert not isinstance(base_cell, BidirectionalCell), \
            "BidirectionalCell doesn't support zoneout. Apply ZoneoutCell to " \
            "the cells underneath instead."
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self._prev_output = None

    def _alias(self):
        return "zoneout"

    def reset(self):
        super().reset()
        self._prev_output = None

    def hybrid_forward(self, F, inputs, states):
        cell, p_outputs, p_states = self.base_cell, self.zoneout_outputs, \
            self.zoneout_states
        next_output, next_states = cell(inputs, states)

        def mask(p, like):
            return F.Dropout(F.ones_like(like), p=p)
        prev_output = self._prev_output if self._prev_output is not None \
            else F.zeros_like(next_output)
        output = F.where(mask(p_outputs, next_output), next_output, prev_output) \
            if p_outputs != 0.0 else next_output
        new_states = [F.where(mask(p_states, new_s), new_s, old_s)
                      for new_s, old_s in zip(next_states, states)] \
            if p_states != 0.0 else next_states
        self._prev_output = output
        return output, new_states


class ResidualCell(ModifierCell):
    def __init__(self, base_cell):
        super().__init__(base_cell)

    def _alias(self):
        return "residual"

    def hybrid_forward(self, F, inputs, states):
        output, states = self.base_cell(inputs, states)
        output = output + inputs
        return output, states


class BidirectionalCell(HybridRecurrentCell):
    """Run two cells over the sequence in both directions
    (ref: rnn_cell.py:BidirectionalCell). Only usable via unroll()."""

    def __init__(self, l_cell, r_cell, output_prefix="bi_"):
        super().__init__(prefix="", params=None)
        self.register_child(l_cell, "l_cell")
        self.register_child(r_cell, "r_cell")
        self._output_prefix = output_prefix

    def __call__(self, inputs, states):
        raise MXNetError("Bidirectional cannot be stepped. Please use unroll")

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children.values(), batch_size)

    def begin_state(self, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._children.values(), **kwargs)

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        from ... import ndarray as F
        self.reset()
        inputs, axis, batch_size = _format_sequence(length, inputs, layout, False)
        if begin_state is None:
            begin_state = self.begin_state(batch_size=batch_size)
        states = begin_state
        l_cell, r_cell = self._children.values()
        n_l = len(l_cell.state_info(batch_size))
        l_outputs, l_states = l_cell.unroll(
            length, inputs=inputs, begin_state=states[:n_l], layout=layout,
            merge_outputs=False, valid_length=valid_length)
        r_inputs = list(reversed(inputs))
        r_outputs, r_states = r_cell.unroll(
            length, inputs=r_inputs, begin_state=states[n_l:], layout=layout,
            merge_outputs=False, valid_length=valid_length)
        r_outputs = list(reversed(r_outputs))
        outputs = [F.concat(l_o, r_o, dim=1)
                   for l_o, r_o in zip(l_outputs, r_outputs)]
        if merge_outputs:
            outputs = F.stack(*outputs, axis=axis)
        states = l_states + r_states
        return outputs, states
