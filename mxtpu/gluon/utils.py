"""Gluon utilities (ref: python/mxnet/gluon/utils.py)."""
from __future__ import annotations

import jax.numpy as jnp

from ..base import MXNetError
from ..ndarray import NDArray


def split_data(data, num_slice, batch_axis=0, even_split=True):
    """Split along batch axis into num_slice chunks (ref: utils.py:split_data)."""
    size = data.shape[batch_axis]
    if even_split and size % num_slice != 0:
        raise MXNetError(
            "data with shape %s cannot be evenly split into %d slices along axis %d. "
            "Use a batch size that's a multiple of %d or set even_split=False."
            % (str(data.shape), num_slice, batch_axis, num_slice))
    step = size // num_slice
    if not even_split and size < num_slice:
        step = 1
        num_slice = size
    slices = []
    for i in range(num_slice):
        lo = i * step
        hi = (i + 1) * step if i < num_slice - 1 else size
        idx = [slice(None)] * data.ndim
        idx[batch_axis] = slice(lo, hi)
        slices.append(data[tuple(idx)])
    return slices


def split_and_load(data, ctx_list, batch_axis=0, even_split=True):
    """Split and place on each ctx (ref: utils.py:split_and_load). On TPU the
    mesh-sharded path (mxtpu.parallel) supersedes per-ctx copies; this keeps the
    multi-device-loop API working."""
    from ..ndarray import array
    if not isinstance(data, NDArray):
        data = array(data)
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [s.as_in_context(ctx) for s, ctx in zip(slices, ctx_list)]


def clip_global_norm(arrays, max_norm, check_isfinite=True):
    """Rescale arrays so that the sum of their 2-norm is smaller than max_norm
    (ref: utils.py:clip_global_norm)."""
    if not arrays:
        raise MXNetError("arrays must not be empty")
    total = jnp.sqrt(sum(jnp.sum(jnp.square(a._data.astype(jnp.float32)))
                         for a in arrays))
    total_f = float(total)
    if check_isfinite and not jnp.isfinite(total_f):
        import warnings
        warnings.warn("nan or inf is detected. Clipping results will be undefined.",
                      stacklevel=2)
    scale = max_norm / (total_f + 1e-8)
    if scale < 1.0:
        for a in arrays:
            a._set_data(a._data * scale)
    return total_f


def check_sha1(filename, sha1_hash):
    import hashlib
    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        while True:
            data = f.read(1048576)
            if not data:
                break
            sha1.update(data)
    return sha1.hexdigest() == sha1_hash


def download(url, path=None, overwrite=False, sha1_hash=None):  # pragma: no cover
    raise MXNetError("download() requires network access, which is unavailable "
                     "in this environment")
