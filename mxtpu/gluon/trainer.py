"""Trainer: applies an Optimizer to a set of Parameters.

Reference: ``python/mxnet/gluon/trainer.py:27-423`` — kvstore setup (:158),
``step`` (:254) = _allreduce_grads (kv.push/pull per param, :304) then _update
(per-device Updater, :347), save/load_states (:376).

TPU-native notes: parameters have ONE logical copy (possibly sharded on the mesh),
so `_allreduce_grads` reduces across the mesh via the kvstore's XLA-collective
push/pull rather than across per-GPU copies. ``update_on_kvstore`` semantics are
preserved: True runs the optimizer inside the store (the reference's server-side
update), False runs the updater locally after the reduce.

Mesh-native mode (ISSUE 7): pass ``mesh=`` (or set ``MXTPU_MESH``) and the
Trainer becomes the multi-chip fast path the reference's CommDevice/ps-lite
machinery approximated — parameters and optimizer state get
``NamedSharding``s at ``_init_kvstore`` time (ONE logical replicated copy;
ZeRO-1 data-axis-sharded optimizer state where divisible, arXiv:2004.13336),
:meth:`Trainer.shard_batch` lays the batch on the data axis, and
:meth:`step` routes through the SAME donated FusedUpdater jit taking the
sharded state — gradient reduction is GSPMD dataflow compiled into
backward + the fused update, so the kvstore's device kind degrades to a
thin control-plane view (init/broadcast/embedding pulls) over those
collectives. The whole optimizer zoo, the numerics sentinel, loss scaling,
and orbax checkpointing ride unchanged.
"""
from __future__ import annotations

import os

from .. import optimizer as opt_mod
from .. import telemetry
from ..base import MXNetError
from .parameter import Parameter, ParameterDict

__all__ = ["Trainer"]


class Trainer:
    """``loss_scaler``: an optional :class:`mxtpu.resilience.DynamicLossScaler`.
    Attaching one (or setting ``MXTPU_NUMERICS_GUARD=1``) runs every step
    under the in-jit numerics sentinel: non-finite gradient steps become
    no-ops on params and optimizer state, the scale backs off / regrows
    in-graph, and :meth:`step` returns the device ``step_ok`` scalar
    (fetched asynchronously — no hot-loop host sync). Scale the loss with
    ``scaler.scale(loss)`` before ``backward()``; the unscale happens
    inside the fused update. Scaler state rides save_states/load_states.

    ``mesh``: an optional ``jax.sharding.Mesh`` with a ``data_axis`` axis —
    multi-chip data-parallel training through this Trainer's own step (see
    module docstring). ``MXTPU_MESH=1|auto`` builds one over every visible
    device when the argument is omitted; ``MXTPU_MESH=<n>`` over the first
    n. ``zero1`` (default env ``MXTPU_ZERO1``, on) shards the optimizer
    state and update compute over the data axis — per-replica state bytes
    divide by the axis size, the loss trajectory is bit-identical."""

    def __init__(self, params, optimizer, optimizer_params=None, kvstore="device",
                 compression_params=None, update_on_kvstore=None,
                 loss_scaler=None, mesh=None, zero1=None, data_axis="data"):
        if isinstance(params, (dict, ParameterDict)):
            params = list(params.values())
        if not isinstance(params, (list, tuple)):
            raise MXNetError(
                "First argument must be a list or dict of Parameters, got %s."
                % type(params))
        self._params = []
        for param in params:
            if not isinstance(param, Parameter):
                raise MXNetError(
                    "First argument must be a list or dict of Parameters, got "
                    "list of %s." % type(param))
            param._trainer = self
            self._params.append(param)
        self._compression_params = compression_params
        optimizer_params = optimizer_params or {}
        self._scale = float(optimizer_params.get("rescale_grad", 1.0))
        self._loss_scaler = loss_scaler
        self._init_optimizer(optimizer, optimizer_params)
        if loss_scaler is not None:
            self._updaters[0].scaler = loss_scaler
        self._mesh = self._resolve_mesh(mesh, data_axis)
        self._data_axis = data_axis
        if zero1 is None:
            zero1 = os.environ.get("MXTPU_ZERO1", "1") != "0"
        self._zero1 = bool(zero1) and self._mesh is not None
        if self._mesh is not None:
            if update_on_kvstore:
                raise MXNetError(
                    "update_on_kvstore=True is incompatible with mesh=: the "
                    "mesh-native step IS the store-side update (one logical "
                    "copy, GSPMD collectives inside the fused jit)")
            set_mesh = getattr(self._updaters[0], "set_mesh", None)
            if set_mesh is None:
                raise MXNetError(
                    "mesh= needs a mesh-capable updater (FusedUpdater); got "
                    "%s" % type(self._updaters[0]).__name__)
            set_mesh(self._mesh, data_axis, self._zero1)
        self._kv_initialized = False
        self._kvstore_kind = kvstore
        self._kvstore = None
        self._update_on_kvstore = update_on_kvstore
        # runtime MFU attribution (mxtpu/xprof.py): executed ledger FLOPs
        # over wall clock vs the datasheet peak, gauged as perf.mfu every
        # meter window — pure host bookkeeping, no device work. The mesh
        # trainer's peak is the whole mesh's (matching bench.py's mfu).
        from .. import xprof
        n_dev = self._mesh.devices.size if self._mesh is not None else 1
        self._mfu = xprof.MFUMeter(n_devices=n_dev) \
            if xprof.enabled() else None
        xprof.ensure_memwatch()  # live HBM gauges when MXTPU_MEMWATCH_S>0
        # step-wedge watchdog (ISSUE 14, mxtpu/resilience.py): with
        # MXTPU_TRAIN_STEP_TIMEOUT_X > 0 every step dispatch is bracketed
        # by a deadline off a rolling step-time baseline; a trip dumps
        # flight_record("train_wedge") and fails loud. Off-thread monitor
        # here; tests attach their own fake-clock watchdog and poll().
        from .. import resilience as _res
        self._step_seq = 0
        # last completed step's trace id + per-stage host timings, for
        # the fleet observability board (mxtpu/fleet_obs.py)
        self.last_step_trace = None
        self.last_step_stages = {}
        self._step_watchdog = None
        if _res.train_step_timeout_x() > 0:
            self._step_watchdog = _res.TrainStepWatchdog().start_monitor()

    @staticmethod
    def _resolve_mesh(mesh, data_axis):
        if mesh is not None:
            if data_axis not in mesh.shape:
                raise MXNetError("mesh has no %r axis (axes: %s)"
                                 % (data_axis, tuple(mesh.shape)))
            return mesh
        spec = os.environ.get("MXTPU_MESH", "0")
        if spec in ("", "0"):
            return None
        from ..parallel import mesh as mesh_mod
        if spec in ("1", "auto"):
            return mesh_mod.data_parallel_mesh(axis=data_axis)
        try:
            n = int(spec)
        except ValueError:
            raise MXNetError(
                "MXTPU_MESH=%r: use 1|auto (all visible devices on one "
                "%r axis) or an integer device count" % (spec, data_axis))
        return mesh_mod.make_mesh({data_axis: n})

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: param for i, param in enumerate(self._params)}
        if isinstance(optimizer, opt_mod.Optimizer):
            if optimizer_params:
                raise MXNetError(
                    "optimizer_params must be None if optimizer is an Optimizer "
                    "instance")
            self._optimizer = optimizer
            self._optimizer.param_dict = param_dict
        else:
            self._optimizer = opt_mod.create(optimizer, param_dict=param_dict,
                                             **optimizer_params)
        self._updaters = [opt_mod.get_updater(self._optimizer)]

    def _init_kvstore(self):
        if self._mesh is not None:
            self._place_on_mesh()
        if self._kvstore_kind:
            from .. import kvstore as kv_mod
            kv = kv_mod.create(self._kvstore_kind) \
                if isinstance(self._kvstore_kind, str) else self._kvstore_kind
            if self._mesh is not None:
                if "dist" in kv.type:
                    raise MXNetError(
                        "mesh= with a dist_* kvstore is contradictory: a "
                        "multi-host mesh IS the distributed path (one mesh "
                        "spanning jax.distributed processes, collectives "
                        "over DCN) — use a device kvstore kind and a "
                        "multi-process mesh instead")
                kv.attach_mesh(self._mesh)
            if self._compression_params:
                kv.set_gradient_compression(self._compression_params)
            update_on_kvstore = self._update_on_kvstore
            if update_on_kvstore is None:
                update_on_kvstore = self._mesh is None and "dist" in kv.type
            for i, param in enumerate(self._params):
                if param._data is not None:
                    kv.init(i, param.data())
            if update_on_kvstore:
                kv.set_optimizer(self._optimizer)
                if self._loss_scaler is not None and \
                        getattr(kv, "_updater", None) is not None:
                    kv._updater.scaler = self._loss_scaler
            self._kvstore = kv
            self._update_on_kvstore = update_on_kvstore
        else:
            self._kvstore = None
            self._update_on_kvstore = False
        self._kv_initialized = True

    def _place_on_mesh(self):
        """Mesh-native placement (module docstring): every parameter (and
        its gradient buffer) becomes ONE logical replicated array laid out
        on the mesh, and the optimizer state is created NOW and placed by
        the updater's MeshPlan — ZeRO-1 data-axis shards where dim 0
        divides, replicated otherwise. Runs once, at kvstore-init time,
        exactly where the reference bound parameters to its store."""
        from jax.sharding import NamedSharding, PartitionSpec
        from ..parallel.mesh import place_global
        repl = NamedSharding(self._mesh, PartitionSpec())
        updater = self._updaters[0]
        ensure = getattr(updater, "ensure_state", None)
        for i, param in enumerate(self._params):
            if param._data is None:
                continue
            d = param.data()
            # place_global: identical device_put single-process; on a
            # process-spanning fleet mesh it builds the replicated global
            # array from this host's copy (device_put cannot)
            d._set_data(place_global(d._data, repl))
            if d._grad is not None:
                d._grad._set_data(place_global(d._grad._data, repl))
            if ensure is not None and param.grad_req != "null":
                ensure(i, d)

    @property
    def batch_sharding(self):
        """The mesh batch layout (``NamedSharding`` over the data axis,
        dim 0) that :meth:`shard_batch` places inputs on — or None
        without a mesh. The input pipeline's prefetch-to-device stage
        (``mxtpu/io/stream.py``, ``DataLoader(prefetch_to_device=
        trainer)``) device_puts each incoming batch directly onto THIS
        sharding, so per-replica slices land on their devices with no
        host-side gather and the training step sees the exact layout
        ``shard_batch`` would have produced."""
        if self._mesh is None:
            return None
        from jax.sharding import NamedSharding, PartitionSpec
        return NamedSharding(self._mesh, PartitionSpec(self._data_axis))

    def shard_batch(self, *arrays):
        """Place batch array(s) sharded over the mesh data axis (dim 0) —
        the per-step input layout of mesh-native training. Without a mesh
        this is the identity, so loops can call it unconditionally.
        Returns one NDArray per input (a single input returns a single
        NDArray)."""
        from ..ndarray import NDArray
        if self._mesh is None:
            return arrays[0] if len(arrays) == 1 else tuple(arrays)
        import jax
        import jax.numpy as jnp
        from ..parallel.mesh import is_multiprocess_mesh
        sh = self.batch_sharding
        n = self._mesh.shape[self._data_axis]
        multiproc = is_multiprocess_mesh(self._mesh)
        world = len({d.process_index for d in self._mesh.devices.flat}) \
            if multiproc else 1
        out = []
        for a in arrays:
            d = a._data if isinstance(a, NDArray) else jnp.asarray(a)
            global_rows = (d.shape[0] * world) if d.shape else None
            if not d.shape or global_rows % n:
                raise MXNetError(
                    "batch dim %s does not divide the %r mesh axis (%d)"
                    % ((global_rows,) if d.shape else "<scalar>",
                       self._data_axis, n))
            if multiproc:
                # fleet: each host holds ITS slice of the global batch
                # (Fleet.data_shard determinism); assemble the global
                # array from the per-host shards — device_put cannot
                # write shards on devices this host does not address
                import numpy as np
                from jax.experimental import multihost_utils
                g = multihost_utils.host_local_array_to_global_array(
                    np.asarray(d), self._mesh, sh.spec)
                out.append(NDArray(g))
            else:
                out.append(NDArray(jax.device_put(d, sh)))
        return out[0] if len(out) == 1 else tuple(out)

    @property
    def learning_rate(self):
        return self._optimizer.learning_rate

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    @property
    def optimizer(self):
        return self._optimizer

    def attach_step_watchdog(self, watchdog):
        """Attach a :class:`mxtpu.resilience.TrainStepWatchdog` (or detach
        with None). The env path (``MXTPU_TRAIN_STEP_TIMEOUT_X``) builds a
        monitor-threaded one at construction; tests attach a fake-clock
        instance and drive :meth:`~mxtpu.resilience.TrainStepWatchdog.poll`
        — the whole wedge matrix runs sleep-free. A replaced watchdog's
        monitor thread is stopped (it would otherwise scan the orphan
        until process exit)."""
        old = self._step_watchdog
        if old is not None and old is not watchdog:
            old.stop_monitor()
        self._step_watchdog = watchdog
        return watchdog

    def step(self, batch_size, ignore_stale_grad=False):
        """One optimization step (ref: trainer.py:254). rescale_grad is set to
        1/batch_size on top of any user scale, like the reference.

        Under the numerics sentinel (loss_scaler attached or
        MXTPU_NUMERICS_GUARD=1) returns the step's ``step_ok`` verdict as a
        lazy device NDArray — fetched asynchronously, so reading it later
        (or never) adds no hot-loop sync; unguarded steps return None.

        Step-phase timeline (mxtpu/telemetry.py): the whole step and its
        allreduce/update phases are recorded as host spans — pure host
        timers, zero device work, so the zero-sync contract above holds
        with telemetry enabled. The outer span tracks the d2h counter:
        a device->host sync inside a steady-state step trips the transfer
        watchdog.

        Causal tracing (MXTPU_TRACE, default on): each step is a trace
        ROOT — allreduce/update nest as children, and the input
        pipeline's ``data.wait``/``data.h2d`` events for the batch this
        step consumes (recorded on the loader/prefetch-producer threads,
        pended at hand-over) attach as cross-thread links, so a slow step
        is attributable to data vs compute from one tree. All of it is
        host bookkeeping: the d2h==0 contract holds with tracing ON
        (pinned by the transfer-guard test parametrized over MXTPU_TRACE)."""
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        from .. import resilience, xprof
        with telemetry.span("trainer.step", d2h=True, new_trace=True):
            # attach the producer-thread data events (data.wait/data.h2d
            # pended by the loader when it handed this batch over) to
            # THIS step's trace as causal links
            telemetry.link_pending()
            # wedge-watchdog bracket: arm with THIS step's trace id so a
            # trip's flight artifact names the wedged step's trace. Pure
            # host bookkeeping (a clock read + list append) — the d2h==0
            # contract holds with the watchdog attached.
            self._step_seq += 1
            wd = self._step_watchdog
            entry = None
            if wd is not None:
                ctx = telemetry.current_trace()
                entry = wd.arm(self._step_seq,
                               None if ctx is None else ctx.trace_id)
            try:
                resilience.maybe_oom()
                import time as _time
                _t0 = _time.perf_counter()
                with telemetry.span("trainer.step.allreduce"):
                    self._allreduce_grads()
                _t1 = _time.perf_counter()
                with telemetry.span("trainer.step.update"):
                    self._update(ignore_stale_grad)
                _t2 = _time.perf_counter()
                # fleet trace stitching (mxtpu/fleet_obs.py): fold the
                # phase durations into the step trace's stage accumulator
                # and pin them (plus the trace id) on the trainer, so the
                # fleet worker can ship this host's per-stage breakdown
                # over the step-barrier board. Host clock reads only.
                ctx = telemetry.current_trace()
                stages = {"trainer.step.allreduce": _t1 - _t0,
                          "trainer.step.update": _t2 - _t1}
                for _name, _dur in stages.items():
                    telemetry.add_stage(ctx, _name, _dur)
                self.last_step_trace = None if ctx is None else ctx.trace_id
                self.last_step_stages = stages
            except Exception as e:
                if entry is not None:
                    try:
                        wd.disarm(entry)
                    except resilience.TrainWedgeError:
                        pass  # the original dispatch error stays loud
                if xprof.is_oom(e):
                    # an HBM OOM must leave an artifact, not just a dead
                    # process: ledger + per-device memory stats dump
                    # before the failure propagates loud
                    ctx = telemetry.current_trace()
                    xprof.oom_flight(
                        "trainer.step", e,
                        trace_ids=[ctx.trace_id] if ctx else [])
                raise
            if entry is not None:
                wd.disarm(entry)  # raises loud if this step tripped
            if self._mfu is not None:
                self._mfu.step()  # host bookkeeping only: perf.mfu gauge
            return self._step_verdict()

    def _active_updater(self):
        if self._update_on_kvstore and self._kvstore is not None:
            return getattr(self._kvstore, "_updater", None)
        return self._updaters[0]

    def _step_verdict(self):
        import jax.numpy as jnp

        from ..ndarray import NDArray
        upd = self._active_updater()
        ok = getattr(upd, "last_step_ok", None)
        return None if ok is None else NDArray(jnp.asarray(ok))

    def allreduce_grads(self):
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            raise MXNetError("allreduce_grads() when parameters are updated on "
                             "kvstore is not supported")
        self._allreduce_grads()

    def _allreduce_grads(self):
        if self._kvstore is None:
            return
        if self._mesh is not None:
            # mesh-native fast path: there is ONE logical mesh-laid-out
            # copy of every gradient and the cross-device reduction is
            # GSPMD dataflow compiled into backward + the fused update —
            # the push/pull round trip through the store would only add
            # host-driven copies. Push/pull stay available as the control
            # plane (init/broadcast/embedding pulls) on the attached mesh.
            return
        # ONE grouped push per step: keys pushed together fuse into a
        # single flattened DCN allreduce per dtype inside the dist kvstore
        # (KVStore._dist_reduce), so the step costs O(1) network round
        # trips instead of O(params) (VERDICT r4 item 8)
        keys = [i for i, p in enumerate(self._params)
                if p.grad_req != "null"]
        if not keys:
            return
        params = [self._params[i] for i in keys]
        if self._update_on_kvstore:
            # push grads; pull back the updated weights (store-side update)
            self._kvstore.push(keys, [p.list_grad() for p in params])
            self._kvstore.pull(keys, [p.list_data() for p in params])
        else:
            self._kvstore.push(keys, [p.list_grad() for p in params])
            self._kvstore.pull(keys, [p.list_grad() for p in params],
                               ignore_sparse=False)

    def update(self, batch_size, ignore_stale_grad=False):
        if not self._kv_initialized:
            self._init_kvstore()
        if self._kvstore and self._update_on_kvstore:
            raise MXNetError("update() when parameters are updated on kvstore "
                             "is not supported")
        self._optimizer.rescale_grad = self._scale / batch_size
        with telemetry.span("trainer.step.update"):
            self._update(ignore_stale_grad)
        return self._step_verdict()

    def _update(self, ignore_stale_grad=False):
        if self._update_on_kvstore:
            return  # weights already updated by the store during push/pull
        # ONE batched update call: FusedUpdater compiles the whole parameter
        # list into a single donated jit (mxtpu/optimizer_fused.py) instead
        # of 3-10 dispatches per param; sparse grads fall back per-item
        updater = self._updaters[0]
        indices, grads, weights = [], [], []
        for i, param in enumerate(self._params):
            if param.grad_req == "null":
                continue
            if not ignore_stale_grad and param._data is None:
                raise MXNetError("Parameter %s was not initialized" % param.name)
            indices.append(i)
            grads.append(param.grad())
            weights.append(param.data())
        if indices:
            updater.update_batch(indices, grads, weights)

    def save_states(self, fname):
        """Save optimizer/updater states (ref: trainer.py:376)."""
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            self._kvstore.save_optimizer_states(fname, dump_optimizer=True)
        else:
            with open(fname, "wb") as f:
                f.write(self._updaters[0].get_states(dump_optimizer=True))

    def load_states(self, fname):
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            self._kvstore.load_optimizer_states(fname)
            self._optimizer = self._kvstore._updater.optimizer
        else:
            with open(fname, "rb") as f:
                states = f.read()
            for updater in self._updaters:
                updater.set_states(states)
                updater.optimizer = self._optimizer
        self._optimizer.param_dict = {i: p for i, p in enumerate(self._params)}
        # with param_dict rebound, restored states can go back onto the
        # MeshPlan (ZeRO eligibility needs the weight's dim 0, which the
        # blob's stripped param_dict could not provide inside set_states)
        for updater in self._updaters:
            replace = getattr(updater, "_replace_states_on_plan", None)
            if replace is not None:
                replace()
