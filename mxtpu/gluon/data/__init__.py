"""Gluon data API (ref: python/mxnet/gluon/data/)."""
from .dataset import (ArrayDataset, Dataset, RecordFileDataset, SimpleDataset)
from .sampler import (BatchSampler, RandomSampler, Sampler, SequentialSampler)
from .dataloader import DataLoader
from . import vision

__all__ = ["Dataset", "SimpleDataset", "ArrayDataset", "RecordFileDataset",
           "Sampler", "SequentialSampler", "RandomSampler", "BatchSampler",
           "DataLoader", "vision"]
