"""DataLoader worker-process internals — deliberately free of any mxtpu
import. Spawned workers import THIS module (plus numpy) at startup; keeping
mxtpu/jax out of the chain turns a multi-second interpreter spin-up into
milliseconds and guarantees a worker can never initialize an XLA backend
(and therefore never claims the TPU). The parent-side DataLoader in
dataloader.py wraps these primitives.

Reference analog: python/mxnet/gluon/data/dataloader.py:26-120 — worker
processes hand decoded batches to the trainer through shared memory
(cpu_shared NDArrays there; POSIX shared_memory segments here).
"""
from __future__ import annotations

import traceback
from multiprocessing import shared_memory as _shm

import numpy as np


def default_mp_batchify_fn(data):
    """Worker-side batchify: numpy in, numpy out (ref:
    default_mp_batchify_fn, which batched into cpu_shared NDArrays).
    Runs inside a spawned worker, so it must never touch jax — device
    arrays are rejected loudly instead of deadlocking."""
    first = data[0]
    if hasattr(first, "asnumpy") or hasattr(first, "_data"):
        raise TypeError(
            "multiprocess DataLoader workers require numpy samples "
            "(device arrays cannot cross process boundaries); return numpy "
            "from the dataset/transform or use thread_pool=True")
    if isinstance(first, tuple):
        transposed = list(zip(*data))
        return [default_mp_batchify_fn(list(x)) for x in transposed]
    return np.asarray(data)


def to_shm(obj, segments):
    """numpy payload -> picklable descriptor tree; arrays move into fresh
    shared-memory segments recorded in ``segments``."""
    if isinstance(obj, np.ndarray):
        if obj.nbytes == 0:
            return ("npy0", obj.shape, obj.dtype.str)
        seg = _shm.SharedMemory(create=True, size=obj.nbytes)
        np.ndarray(obj.shape, obj.dtype, buffer=seg.buf)[...] = obj
        # ownership transfers to the consumer (parent unlinks after
        # mapping); unregister from THIS process's resource tracker or it
        # warns about "leaked" segments the parent already removed
        try:
            from multiprocessing import resource_tracker
            resource_tracker.unregister(seg._name, "shared_memory")
        except Exception:  # pragma: no cover - tracker API private-ish
            pass
        segments.append(seg)
        return ("npy", seg.name, obj.shape, obj.dtype.str)
    if isinstance(obj, (list, tuple)):
        return ("seq", type(obj) is tuple, [to_shm(o, segments) for o in obj])
    if obj is None or isinstance(obj, (str, bytes, int, float, bool,
                                       np.generic)):
        return ("raw", obj)
    # anything else (device arrays, custom objects) must fail HERE, as a
    # catchable worker error — letting it reach mp.Queue's feeder thread
    # turns a pickle failure into a silently dropped result and a parent
    # that waits forever
    raise TypeError(
        "multiprocess DataLoader batch contains %r — workers require "
        "numpy samples/batches (device arrays cannot cross process "
        "boundaries); return numpy from the dataset/batchify_fn or use "
        "thread_pool=True" % type(obj).__name__)


def from_shm(desc, wrap):
    """Descriptor tree -> wrapped-array tree (parent side). Each segment is
    mapped, copied off before unmapping (wrap() may device-put
    asynchronously; an async copy racing the munmap reads garbage), then
    closed and unlinked."""
    kind = desc[0]
    if kind == "npy0":
        return wrap(np.empty(desc[1], np.dtype(desc[2])))
    if kind == "npy":
        seg = _shm.SharedMemory(name=desc[1])
        try:
            view = np.ndarray(desc[2], np.dtype(desc[3]), buffer=seg.buf)
            host = np.array(view)
        finally:
            seg.close()
            seg.unlink()
        return wrap(host)
    if kind == "seq":
        items = [from_shm(d, wrap) for d in desc[2]]
        return tuple(items) if desc[1] else items
    return desc[1]


def discard_segments(desc):
    """Unlink every segment in a descriptor tree the consumer never mapped."""
    if desc[0] == "npy":
        try:
            seg = _shm.SharedMemory(name=desc[1])
            seg.close()
            seg.unlink()
        except FileNotFoundError:  # pragma: no cover
            pass
    elif desc[0] == "seq":
        for d in desc[2]:
            discard_segments(d)


def worker_loop(dataset, batchify_fn, task_q, result_q):
    """Spawned worker: pull (batch_index, sample_indices), build the batch
    with numpy, publish via shared memory. Exceptions travel back as
    formatted tracebacks (the reference's worker does the same re-raise
    dance through the ForkingPickler)."""
    while True:
        job = task_q.get()
        if job is None:
            return
        i, idxs = job
        try:
            batch = batchify_fn([dataset[j] for j in idxs])
            segments = []
            desc = to_shm(batch, segments)
            for seg in segments:
                seg.close()  # parent unlinks after mapping
            result_q.put((i, desc, None))
        except Exception:  # pragma: no cover - exercised via parent raise
            result_q.put((i, None, traceback.format_exc()))
