"""DataLoader (ref: python/mxnet/gluon/data/dataloader.py).

TPU-native notes: the reference forks multiprocessing workers that decode into
shared-memory NDArrays; here workers are a thread pool (decode/augment release
the GIL inside numpy/jax) feeding a bounded prefetch queue, and the batch
crosses to the device once at the jit boundary. The `num_workers` /
`batchify_fn` / sampler surface is unchanged.
"""
from __future__ import annotations

import queue as _queue
import threading

import numpy as np

from ...ndarray import NDArray, array
from .sampler import BatchSampler, RandomSampler, SequentialSampler

__all__ = ["DataLoader", "default_batchify_fn"]


def default_batchify_fn(data):
    """Stack samples into a batch (ref: dataloader.py:default_batchify_fn)."""
    if isinstance(data[0], NDArray):
        from ...ndarray import stack
        return stack(*data)
    if isinstance(data[0], tuple):
        transposed = list(zip(*data))
        return [default_batchify_fn(list(x)) for x in transposed]
    data = np.asarray(data)
    return array(data)


class DataLoader:
    """Iterate a Dataset in mini-batches (ref: dataloader.py:DataLoader)."""

    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, prefetch=None):
        self._dataset = dataset
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError("batch_size is required when batch_sampler "
                                 "is not specified")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle else \
                    SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError("shuffle must be False with a sampler")
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch or "keep")
        elif batch_size is not None or shuffle or sampler is not None or \
                last_batch is not None:
            raise ValueError(
                "batch_size/shuffle/sampler/last_batch must not be set "
                "when batch_sampler is specified")
        self._batch_sampler = batch_sampler
        self._batchify_fn = batchify_fn or default_batchify_fn
        self._num_workers = max(0, num_workers)
        self._prefetch = max(0, prefetch if prefetch is not None
                             else 2 * self._num_workers)

    def __len__(self):
        return len(self._batch_sampler)

    def _load(self, batch_idx):
        return self._batchify_fn([self._dataset[i] for i in batch_idx])

    def __iter__(self):
        if self._num_workers == 0:
            for batch_idx in self._batch_sampler:
                yield self._load(batch_idx)
            return

        # thread-pool pipeline with ordered delivery
        batches = list(self._batch_sampler)
        results = {}
        results_lock = threading.Lock()
        results_ready = threading.Condition(results_lock)
        work = _queue.Queue()
        for i, b in enumerate(batches):
            work.put((i, b))
        stop = threading.Event()

        bound = max(self._prefetch, self._num_workers, 1)
        state = {"next": 0}  # next batch index the consumer will take

        def worker():
            while not stop.is_set():
                try:
                    i, b = work.get_nowait()
                except _queue.Empty:
                    return
                # bounded prefetch: never decode more than `bound` batches
                # ahead of the consumer (reference: dataloader prefetch).
                # Throttling on distance-from-consumer (not on len(results))
                # cannot block the batch the consumer needs next.
                with results_ready:
                    while i > state["next"] + bound and not stop.is_set():
                        results_ready.wait(0.1)
                if stop.is_set():
                    return
                try:
                    out = self._load(b)
                except Exception as e:  # surfaced at delivery
                    out = e
                with results_ready:
                    results[i] = out
                    results_ready.notify_all()

        threads = [threading.Thread(target=worker, daemon=True)
                   for _ in range(self._num_workers)]
        for t in threads:
            t.start()
        try:
            for i in range(len(batches)):
                with results_ready:
                    while i not in results:
                        results_ready.wait()
                    out = results.pop(i)
                    state["next"] = i + 1
                    results_ready.notify_all()  # release throttled workers
                if isinstance(out, Exception):
                    raise out
                yield out
        finally:
            stop.set()
