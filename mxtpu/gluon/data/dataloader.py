"""DataLoader (ref: python/mxnet/gluon/data/dataloader.py).

TPU-native worker design. The reference forks multiprocessing workers that
decode into shared-memory NDArrays and ship fd handles through a
ForkingPickler (dataloader.py:26-120). The equivalent here:

* ``num_workers>0`` runs worker PROCESSES; each runs dataset[i] + a
  numpy-only batchify and writes the batch into POSIX shared memory
  (`multiprocessing.shared_memory`), sending only (name, shape, dtype)
  descriptors through the result queue — pixel bytes never pass through a
  pickle stream, matching the reference's cpu_shared NDArray handoff.
* the parent maps each segment zero-copy, uploads to the device at the
  jit boundary (the one unavoidable copy), and unlinks it.
* workers use the SPAWN start method, not fork: XLA's runtime threads do
  not survive a fork (jax segfaults/deadlocks, and warns so). Spawned
  workers are persistent per DataLoader — created lazily on the first
  iteration and reused across epochs to amortize interpreter startup —
  and must stay in numpy land (the worker batchify rejects device arrays
  with a loud error; a worker that never calls jax never initializes a
  backend, so it also never claims the TPU).
* ``thread_pool=True`` selects the thread-based pipeline instead
  (decode/augment release the GIL inside numpy/cv2) — same surface, no
  spawn/pickling constraint on the dataset.
"""
from __future__ import annotations

import multiprocessing as _mp
import os
import queue as _queue
import threading
import time
import warnings

import numpy as np

from ...ndarray import NDArray, array
from . import _mp_worker
from ._mp_worker import default_mp_batchify_fn  # noqa: F401 (public re-export)
from .sampler import BatchSampler, RandomSampler, SequentialSampler

__all__ = ["DataLoader", "default_batchify_fn", "default_mp_batchify_fn"]


def _prefetch_batchify_fn(data):
    """Stacking WITHOUT the eager device placement: numpy samples stay
    numpy so the DevicePrefetcher's async device_put onto the TARGET
    sharding is the one H2D copy; NDArray samples (already
    device-resident) still stack the normal way — the in-process paths
    must keep accepting them (the mp pool rejects them regardless, in
    the worker). `default_batchify_fn` is this plus the leaf wrap."""
    if isinstance(data[0], NDArray):
        from ...ndarray import stack
        return stack(*data)
    if isinstance(data[0], tuple):
        transposed = list(zip(*data))
        return [_prefetch_batchify_fn(list(x)) for x in transposed]
    return np.asarray(data)


def default_batchify_fn(data):
    """Stack samples into a batch (ref: dataloader.py:default_batchify_fn)."""
    def wrap(x):
        if isinstance(x, list):
            return [wrap(v) for v in x]
        return array(x) if isinstance(x, np.ndarray) else x
    return wrap(_prefetch_batchify_fn(data))


# worker-process internals (numpy-only, no mxtpu import) live in
# _mp_worker.py so a spawned worker never pays the jax/mxtpu import —
# see that module's docstring for the shared-memory protocol


class DataLoader:
    """Iterate a Dataset in mini-batches (ref: dataloader.py:DataLoader)."""

    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, prefetch=None,
                 thread_pool=False, prefetch_to_device=None):
        self._dataset = dataset
        self._thread_pool = thread_pool
        self._pool = None  # lazy persistent spawn-worker pool
        # prefetch_to_device (ISSUE 9): None/False = classic host batches;
        # True = double-buffered async device_put of batch N+1 while the
        # consumer computes on batch N (mxtpu/io/stream.DevicePrefetcher,
        # depth MXTPU_PREFETCH_DEPTH); a jax Sharding or a mesh
        # gluon.Trainer lands each per-replica slice directly on its
        # device (Trainer.batch_sharding) — no host-side gather. With it
        # on, `data.wait` measures only TRUE starvation (buffer-empty)
        # and `data.h2d` times the transfers (docs/data_pipeline.md).
        self._prefetch_spec = prefetch_to_device \
            if prefetch_to_device not in (None, False) else None
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError("batch_size is required when batch_sampler "
                                 "is not specified")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle else \
                    SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError("shuffle must be False with a sampler")
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch or "keep")
        elif batch_size is not None or shuffle or sampler is not None or \
                last_batch is not None:
            raise ValueError(
                "batch_size/shuffle/sampler/last_batch must not be set "
                "when batch_sampler is specified")
        self._batch_sampler = batch_sampler
        self._user_batchify = batchify_fn is not None
        # with the device prefetcher on, default batchify keeps numpy
        # leaves in numpy: the ONE host->device copy is the prefetcher's
        # async device_put onto the target sharding (default_batchify_fn
        # would eagerly place batches on the default device first — a
        # wasted hop); NDArray-sample datasets still stack fine
        self._batchify_fn = batchify_fn or (
            _prefetch_batchify_fn if self._prefetch_spec is not None
            else default_batchify_fn)
        self._num_workers = max(0, num_workers)
        self._prefetch = max(0, prefetch if prefetch is not None
                             else 2 * self._num_workers)

    def __len__(self):
        return len(self._batch_sampler)

    def _load(self, batch_idx):
        return self._batchify_fn([self._dataset[i] for i in batch_idx])

    def __iter__(self):
        from ... import telemetry
        if self._prefetch_spec is not None:
            # device-resident path: the prefetcher owns the data.wait /
            # data.starved / data.h2d telemetry — data.wait then measures
            # only TRUE starvation (consumer blocked on an empty buffer),
            # not decode time the overlap already hid
            from ...io.stream import DevicePrefetcher
            pf = DevicePrefetcher(self._iter_impl(),
                                  sharding=self._prefetch_spec)
            try:
                yield from pf
            finally:
                pf.close()
            return
        it = self._iter_impl()
        while True:
            # data-wait phase of the step timeline: how long the consumer
            # blocked on the input pipeline before each batch (span
            # "data.wait" in telemetry/profiler.dump — the host-side
            # analog of the reference profiler's engine queue time)
            with telemetry.span("data.wait", new_trace=True) as sp:
                try:
                    batch = next(it)
                except StopIteration:
                    return
            # pend the wait for the consuming step's trace to link
            # (telemetry.link_pending inside Trainer.step)
            telemetry.pend_link("data.wait", sp.ctx)
            yield batch

    def _iter_impl(self):
        if self._num_workers == 0:
            for batch_idx in self._batch_sampler:
                yield self._load(batch_idx)
            return
        if not self._thread_pool:
            yield from self._iter_multiprocess()
            return
        yield from self._iter_threads()

    # ------------------------------------------------- multiprocess workers
    def _ensure_pool(self):
        if self._pool is not None:
            return self._pool
        ctx = _mp.get_context("spawn")
        task_q = ctx.Queue()
        result_q = ctx.Queue()
        batchify = self._batchify_fn if self._user_batchify \
            else default_mp_batchify_fn
        workers = []
        try:
            for _ in range(self._num_workers):
                w = ctx.Process(target=_mp_worker.worker_loop,
                                args=(self._dataset, batchify, task_q,
                                      result_q), daemon=True)
                w.start()
                workers.append(w)
        except Exception as e:  # dataset/batchify not picklable for spawn
            for w in workers:  # don't orphan the ones that DID start
                w.terminate()
                w.join(timeout=5)
            warnings.warn("DataLoader cannot spawn workers (%s): falling "
                          "back to thread workers" % e)
            self._thread_pool = True
            return None
        self._pool = (task_q, result_q, workers)
        self._seq = 0  # monotone task ids: stale results from an aborted
        # epoch must never satisfy the next epoch's wait
        return self._pool

    def _teardown_pool(self, task_q, result_q, workers, join_timeout,
                       drain_timeout):
        """ONE copy of the pool teardown shared by close() and the
        worker-death rebuild: bounded joins (terminate stragglers), drain
        published results reclaiming their shm segments, then close +
        ``cancel_join_thread()`` both queues so a feeder thread can never
        hang interpreter exit."""
        # join BEFORE draining: a worker's queue feeder thread may still be
        # flushing a result; draining first would miss it and leak its
        # shared-memory segments (mp.Queue is unbounded, so joining here
        # cannot deadlock on a full queue)
        for w in workers:
            w.join(timeout=join_timeout)
            if w.is_alive():  # pragma: no cover - stuck worker
                w.terminate()
                w.join(timeout=1.0)
        while True:
            try:
                _j, desc, err = result_q.get(timeout=drain_timeout)
            except Exception:  # Empty, or a torn frame from a dead writer
                break
            if err is None:
                self._discard_segments(desc)
        for q in (task_q, result_q):  # pragma: no branch
            try:
                q.close()
                q.cancel_join_thread()
            except Exception:  # pragma: no cover - queue already torn down
                pass

    def close(self, timeout=5.0):
        """Shut the persistent worker pool down (idempotent). Workers are
        joined with a bounded ``timeout`` and terminated if still alive, and
        both queues get ``cancel_join_thread()`` — a wedged worker or a
        queue feeder thread must never hang interpreter exit (this runs
        from ``__del__`` at teardown)."""
        if self._pool is None:
            return
        task_q, result_q, workers = self._pool
        self._pool = None
        for _ in workers:
            task_q.put(None)
        self._teardown_pool(task_q, result_q, workers, join_timeout=timeout,
                            drain_timeout=0.2)

    def __del__(self):  # pragma: no cover - interpreter-exit timing
        try:
            self.close()
        except Exception:
            pass

    def _rebuild_pool(self):
        """Tear the WHOLE pool down and spawn a fresh one after a worker
        death. A fresh pool (not an in-place replacement) is load-bearing:
        a worker SIGKILLed inside ``task_q.get()`` dies HOLDING the queue's
        shared reader lock — every surviving worker then blocks forever
        acquiring it, so the old queues are poisoned and must be abandoned.
        Already-published results are drained off the old result queue
        (their shm segments reclaimed) before it is dropped."""
        task_q, result_q, workers = self._pool
        self._pool = None
        for w in workers:
            if w.is_alive():  # no sentinels: the queues may be poisoned
                w.terminate()
        self._teardown_pool(task_q, result_q, workers, join_timeout=1.0,
                            drain_timeout=0.1)
        seq = self._seq  # task ids must stay monotone across the rebuild
        pool = self._ensure_pool()
        self._seq = seq
        return pool

    def _iter_multiprocess(self):
        """Spawned worker processes + shared-memory batch handoff (the
        reference's _MultiWorkerIter, dataloader.py:157-231).

        Worker DEATH (OOM-kill, segfault — distinct from a dataset
        exception, which travels back as an error result) is survivable:
        dead workers are restarted with backoff and their lost in-flight
        tasks re-enqueued (duplicate deliveries are discarded), up to
        MXTPU_DL_WORKER_RESTARTS (default 3) restarts per epoch; past that
        the raise reports every exit code and the batch index so the
        failure is attributable. A worker killed mid-publish can leak its
        shared-memory segment — the price of surviving, noted here."""
        pool = self._ensure_pool()
        if pool is None:  # spawn failed: picklability fallback
            yield from self._iter_threads()
            return
        task_q, result_q, _workers = pool
        batches = list(self._batch_sampler)
        base = self._seq
        self._seq += len(batches)
        bound = max(self._prefetch, self._num_workers, 1)
        max_restarts = int(os.environ.get("MXTPU_DL_WORKER_RESTARTS", "3"))
        sent = 0
        restarts = 0
        results = {}
        from ...resilience import inject
        try:
            for i in range(len(batches)):
                # keep at most `bound` batches in flight past the consumer
                while sent < len(batches) and sent < i + bound:
                    task_q.put((base + sent, batches[sent]))
                    sent += 1
                if inject("worker_death", i):
                    import signal as _signal
                    victim = next(
                        (w for w in _workers if w.is_alive()), None)
                    if victim is not None:
                        os.kill(victim.pid, _signal.SIGKILL)
                while base + i not in results:
                    try:
                        j, desc, err = result_q.get(timeout=1.0)
                    except _queue.Empty:
                        dead = [w for w in _workers
                                if not w.is_alive()
                                and w.exitcode not in (0, None)]
                        if not dead:
                            continue
                        # ONE event per detection, however many workers an
                        # OOM-killer sweep took — the budget counts pool
                        # rebuild attempts, not corpses
                        restarts += 1
                        from ... import telemetry
                        telemetry.inc("dataloader.worker_restarts")
                        if restarts > max_restarts:
                            raise RuntimeError(
                                "DataLoader worker(s) died (exit codes %s) "
                                "while waiting for batch %d/%d; giving up "
                                "after %d restart(s) "
                                "(MXTPU_DL_WORKER_RESTARTS=%d). Repeated "
                                "deaths usually mean the OOM killer — "
                                "shrink the batch or worker count."
                                % ([w.exitcode for w in dead], i,
                                   len(batches), restarts - 1,
                                   max_restarts))
                        warnings.warn(
                            "DataLoader worker died (exit codes %s) at "
                            "batch %d; restarting the pool (%d/%d)"
                            % ([w.exitcode for w in dead], i, restarts,
                               max_restarts))
                        time.sleep(0.05 * restarts)  # backoff
                        pool = self._rebuild_pool()
                        if pool is None:  # spawn broke: cannot recover
                            raise RuntimeError(
                                "DataLoader worker died and the pool could "
                                "not be respawned")
                        task_q, result_q, _workers = pool
                        # in-flight work died with the old pool: re-enqueue
                        # every outstanding id (completed drained results
                        # for pending ids were reclaimed by the rebuild,
                        # so a recompute is the only copy)
                        for j2 in range(base + i, base + sent):
                            if j2 not in results:
                                task_q.put((j2, batches[j2 - base]))
                        continue
                    if j < base + i or j in results:
                        # stale epoch, already-yielded, or a post-restart
                        # duplicate: discard — including stale ERRORS,
                        # which belong to work the consumer moved past
                        if err is None:
                            self._discard_segments(desc)
                        continue
                    if err is not None:
                        raise RuntimeError(
                            "DataLoader worker failed at batch %d:\n%s"
                            % (j - base, err))
                    results[j] = desc
                # device-prefetch path: leave leaves in numpy — the
                # prefetcher's device_put is the one H2D copy
                wrap = (lambda x: x) if self._prefetch_spec is not None \
                    else array
                yield _mp_worker.from_shm(results.pop(base + i), wrap)
        finally:
            # unlink any segments the consumer never mapped (early exit);
            # in-flight stale results are discarded by the next epoch/close
            for desc in results.values():
                self._discard_segments(desc)

    @staticmethod
    def _discard_segments(desc):
        _mp_worker.discard_segments(desc)

    # ------------------------------------------------------- thread workers
    def _iter_threads(self):
        # thread-pool pipeline with ordered delivery
        batches = list(self._batch_sampler)
        results = {}
        results_lock = threading.Lock()
        results_ready = threading.Condition(results_lock)
        work = _queue.Queue()
        for i, b in enumerate(batches):
            work.put((i, b))
        stop = threading.Event()

        bound = max(self._prefetch, self._num_workers, 1)
        state = {"next": 0}  # next batch index the consumer will take

        def worker():
            while not stop.is_set():
                try:
                    i, b = work.get_nowait()
                except _queue.Empty:
                    return
                # bounded prefetch: never decode more than `bound` batches
                # ahead of the consumer (reference: dataloader prefetch).
                # Throttling on distance-from-consumer (not on len(results))
                # cannot block the batch the consumer needs next.
                with results_ready:
                    while i > state["next"] + bound and not stop.is_set():
                        results_ready.wait(0.1)
                if stop.is_set():
                    return
                try:
                    out = self._load(b)
                except Exception as e:  # surfaced at delivery
                    out = e
                with results_ready:
                    results[i] = out
                    results_ready.notify_all()

        threads = [threading.Thread(target=worker, daemon=True)
                   for _ in range(self._num_workers)]
        for t in threads:
            t.start()
        try:
            for i in range(len(batches)):
                with results_ready:
                    while i not in results:
                        results_ready.wait()
                    out = results.pop(i)
                    state["next"] = i + 1
                    results_ready.notify_all()  # release throttled workers
                if isinstance(out, Exception):
                    raise out
                yield out
        finally:
            stop.set()
