"""Dataset abstractions (ref: python/mxnet/gluon/data/dataset.py)."""
from __future__ import annotations

from ...base import MXNetError

__all__ = ["Dataset", "SimpleDataset", "ArrayDataset", "RecordFileDataset"]


class Dataset:
    """Abstract dataset: __getitem__ + __len__ (ref: dataset.py:Dataset)."""

    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError

    def transform(self, fn, lazy=True):
        """Return a dataset with fn applied to each sample
        (ref: dataset.py:transform)."""
        trans = _LazyTransformDataset(self, fn)
        if lazy:
            return trans
        return SimpleDataset([trans[i] for i in range(len(trans))])

    def transform_first(self, fn, lazy=True):
        """Apply fn to only the first element of each sample
        (ref: dataset.py:transform_first)."""
        return self.transform(_TransformFirstClosure(fn), lazy)


class SimpleDataset(Dataset):
    def __init__(self, data):
        self._data = data

    def __len__(self):
        return len(self._data)

    def __getitem__(self, idx):
        return self._data[idx]


class _LazyTransformDataset(Dataset):
    def __init__(self, data, fn):
        self._data = data
        self._fn = fn

    def __len__(self):
        return len(self._data)

    def __getitem__(self, idx):
        item = self._data[idx]
        if isinstance(item, tuple):
            return self._fn(*item)
        return self._fn(item)


class _TransformFirstClosure:
    def __init__(self, fn):
        self._fn = fn

    def __call__(self, x, *args):
        if args:
            return (self._fn(x),) + args
        return self._fn(x)


class ArrayDataset(Dataset):
    """Zip of arrays/datasets (ref: dataset.py:ArrayDataset)."""

    def __init__(self, *args):
        if not args:
            raise MXNetError("needs at least 1 array")
        self._length = len(args[0])
        self._data = []
        for i, data in enumerate(args):
            if len(data) != self._length:
                raise MXNetError(
                    "all arrays must have the same length; %d != %d"
                    % (len(data), self._length))
            self._data.append(data)

    def __len__(self):
        return self._length

    def __getitem__(self, idx):
        if len(self._data) == 1:
            return self._data[0][idx]
        return tuple(d[idx] for d in self._data)


class RecordFileDataset(Dataset):
    """Each sample is one raw record from a RecordIO file
    (ref: dataset.py:RecordFileDataset over MXIndexedRecordIO)."""

    def __init__(self, filename):
        from ...recordio import MXIndexedRecordIO
        idx_file = filename[:filename.rfind(".")] + ".idx"
        self._record = MXIndexedRecordIO(idx_file, filename, "r")

    def __len__(self):
        return len(self._record.keys)

    def __getitem__(self, idx):
        return self._record.read_idx(self._record.keys[idx])
