"""Vision datasets (ref: python/mxnet/gluon/data/vision/datasets.py).

Zero-egress environment: datasets read the standard artifact files from
``root`` (the same gzip/binary layouts the reference downloads) and raise a
clear error when absent instead of downloading.
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile

import numpy as np

from ....base import MXNetError
from ....ndarray import array
from ..dataset import ArrayDataset, Dataset, RecordFileDataset

__all__ = ["MNIST", "FashionMNIST", "CIFAR10", "CIFAR100",
           "ImageRecordDataset", "ImageFolderDataset"]


class _DownloadedDataset(Dataset):
    def __init__(self, root, train, transform):
        self._transform = transform
        self._train = train
        self._root = os.path.expanduser(root)
        self._data = None
        self._label = None
        if not os.path.isdir(self._root):
            raise MXNetError(
                "dataset root %s does not exist (no network access: place "
                "the standard dataset files there)" % self._root)
        self._get_data()

    def __getitem__(self, idx):
        if self._transform is not None:
            return self._transform(self._data[idx], self._label[idx])
        return self._data[idx], self._label[idx]

    def __len__(self):
        return len(self._label)

    def _get_data(self):
        raise NotImplementedError


class MNIST(_DownloadedDataset):
    """MNIST from the standard idx-ubyte.gz files (ref: datasets.py:MNIST)."""

    _train_files = ("train-images-idx3-ubyte.gz", "train-labels-idx1-ubyte.gz")
    _test_files = ("t10k-images-idx3-ubyte.gz", "t10k-labels-idx1-ubyte.gz")

    def __init__(self, root="~/.mxnet/datasets/mnist", train=True,
                 transform=None):
        super().__init__(root, train, transform)

    def _get_data(self):
        img_file, lbl_file = self._train_files if self._train \
            else self._test_files
        img_path = os.path.join(self._root, img_file)
        lbl_path = os.path.join(self._root, lbl_file)
        for p in (img_path, lbl_path):
            if not os.path.exists(p):
                raise MXNetError("missing dataset file %s" % p)
        with gzip.open(lbl_path, "rb") as f:
            struct.unpack(">II", f.read(8))
            label = np.frombuffer(f.read(), dtype=np.uint8).astype(np.int32)
        with gzip.open(img_path, "rb") as f:
            _, num, rows, cols = struct.unpack(">IIII", f.read(16))
            data = np.frombuffer(f.read(), dtype=np.uint8).reshape(
                num, rows, cols, 1)
        self._data = array(data)
        self._label = label


class FashionMNIST(MNIST):
    def __init__(self, root="~/.mxnet/datasets/fashion-mnist", train=True,
                 transform=None):
        super().__init__(root, train, transform)


class CIFAR10(_DownloadedDataset):
    """CIFAR-10 from the python pickle batches (cifar-10-batches-py)."""

    def __init__(self, root="~/.mxnet/datasets/cifar10", train=True,
                 transform=None):
        self._classes = 10
        super().__init__(root, train, transform)

    def _batches(self):
        base = os.path.join(self._root, "cifar-10-batches-py")
        if self._train:
            return [os.path.join(base, "data_batch_%d" % i)
                    for i in range(1, 6)]
        return [os.path.join(base, "test_batch")]

    def _get_data(self):
        # auto-extract the tarball if only it is present
        base = os.path.join(self._root, "cifar-10-batches-py")
        tar = os.path.join(self._root, "cifar-10-python.tar.gz")
        if not os.path.isdir(base) and os.path.exists(tar):
            with tarfile.open(tar) as t:
                t.extractall(self._root)
        data, labels = [], []
        for path in self._batches():
            if not os.path.exists(path):
                raise MXNetError("missing dataset file %s" % path)
            with open(path, "rb") as f:
                batch = pickle.load(f, encoding="latin1")
            data.append(batch["data"].reshape(-1, 3, 32, 32))
            labels.extend(batch.get("labels", batch.get("fine_labels")))
        data = np.concatenate(data).transpose(0, 2, 3, 1)  # NHWC like ref
        self._data = array(data)
        self._label = np.asarray(labels, dtype=np.int32)


class CIFAR100(CIFAR10):
    def __init__(self, root="~/.mxnet/datasets/cifar100",
                 fine_label=False, train=True, transform=None):
        self._fine = fine_label
        super().__init__(root, train, transform)
        self._classes = 100

    def _batches(self):
        base = os.path.join(self._root, "cifar-100-python")
        return [os.path.join(base, "train" if self._train else "test")]

    def _get_data(self):
        base = os.path.join(self._root, "cifar-100-python")
        tar = os.path.join(self._root, "cifar-100-python.tar.gz")
        if not os.path.isdir(base) and os.path.exists(tar):
            with tarfile.open(tar) as t:
                t.extractall(self._root)
        path = self._batches()[0]
        if not os.path.exists(path):
            raise MXNetError("missing dataset file %s" % path)
        with open(path, "rb") as f:
            batch = pickle.load(f, encoding="latin1")
        data = batch["data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        key = "fine_labels" if self._fine else "coarse_labels"
        self._data = array(data)
        self._label = np.asarray(batch[key], dtype=np.int32)


class ImageRecordDataset(RecordFileDataset):
    """Images + labels from a RecordIO pack (ref: datasets.py:
    ImageRecordDataset over image/recordio decode)."""

    def __init__(self, filename, flag=1, transform=None):
        super().__init__(filename)
        self._flag = flag
        self._transform = transform

    def __getitem__(self, idx):
        from ....recordio import unpack_img
        record = super().__getitem__(idx)
        header, img = unpack_img(record, iscolor=self._flag)
        if self._transform is not None:
            return self._transform(img, header.label)
        return img, header.label


class ImageFolderDataset(Dataset):
    """label = subfolder index (ref: datasets.py:ImageFolderDataset)."""

    def __init__(self, root, flag=1, transform=None):
        self._root = os.path.expanduser(root)
        self._flag = flag
        self._transform = transform
        self._exts = {".jpg", ".jpeg", ".png", ".bmp", ".npy"}
        self.synsets = []
        self.items = []
        for folder in sorted(os.listdir(self._root)):
            path = os.path.join(self._root, folder)
            if not os.path.isdir(path):
                continue
            label = len(self.synsets)
            self.synsets.append(folder)
            for filename in sorted(os.listdir(path)):
                ext = os.path.splitext(filename)[1].lower()
                if ext in self._exts:
                    self.items.append((os.path.join(path, filename), label))

    def __getitem__(self, idx):
        from ....image import imread
        path, label = self.items[idx]
        if path.endswith(".npy"):
            img = array(np.load(path))
        else:
            img = imread(path, flag=self._flag)
        if self._transform is not None:
            return self._transform(img, label)
        return img, label

    def __len__(self):
        return len(self.items)
