"""Vision transforms (ref: python/mxnet/gluon/data/vision/transforms.py).

Each transform is a Block over the _image_* ops (mxtpu/ops/image_ops.py), so a
transform pipeline is jax-traceable and can fuse under jit.
"""
from __future__ import annotations

import random as _pyrandom

import numpy as np

from ....base import MXNetError, numeric_types
from ....ndarray import NDArray
from ....ndarray import image as _img
from ...block import Block, HybridBlock
from ...nn import HybridSequential

__all__ = ["Compose", "Cast", "ToTensor", "Normalize", "Resize", "CenterCrop",
           "RandomResizedCrop", "RandomFlipLeftRight", "RandomFlipTopBottom",
           "RandomBrightness", "RandomContrast", "RandomSaturation",
           "RandomHue", "RandomColorJitter", "RandomLighting"]


class Compose(HybridSequential):
    """Sequentially compose transforms (ref: transforms.py:Compose)."""

    def __init__(self, transforms):
        super().__init__()
        with self.name_scope():
            for t in transforms:
                self.add(t)


class Cast(HybridBlock):
    def __init__(self, dtype="float32"):
        super().__init__()
        self._dtype = dtype

    def hybrid_forward(self, F, x):
        return x.astype(self._dtype)


class ToTensor(HybridBlock):
    """HWC uint8 -> CHW float32 in [0,1] (ref: transforms.py:ToTensor)."""

    def hybrid_forward(self, F, x):
        return _img.to_tensor(x)


class Normalize(HybridBlock):
    def __init__(self, mean=0.0, std=1.0):
        super().__init__()
        self._mean = mean
        self._std = std

    def hybrid_forward(self, F, x):
        return _img.normalize(x, mean=self._mean, std=self._std)


class Resize(HybridBlock):
    def __init__(self, size, keep_ratio=False, interpolation=1):
        super().__init__()
        self._size = size
        self._keep = keep_ratio
        self._interp = interpolation

    def hybrid_forward(self, F, x):
        size = self._size
        if self._keep and isinstance(size, int):
            h, w = x.shape[-3], x.shape[-2] if x.ndim == 4 else x.shape[1]
            if x.ndim == 3:
                h, w = x.shape[0], x.shape[1]
            scale = size / min(h, w)
            size = (int(round(w * scale)), int(round(h * scale)))
        return _img.resize(x, size=size, interp=self._interp)


class CenterCrop(Block):
    def __init__(self, size, interpolation=1):
        super().__init__()
        self._size = size if not isinstance(size, int) else (size, size)
        self._interp = interpolation

    def forward(self, x):
        w, h = self._size
        H, W = (x.shape[0], x.shape[1]) if x.ndim == 3 else \
            (x.shape[1], x.shape[2])
        if H < h or W < w:
            x = _img.resize(x, size=(max(w, W), max(h, H)),
                            interp=self._interp)
        return _img.center_crop(x, size=self._size)


class RandomResizedCrop(Block):
    """Random area/aspect crop then resize (ref: transforms.py:
    RandomResizedCrop; host-side randomness like the reference's decode
    pipeline)."""

    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation=1):
        super().__init__()
        self._size = size if not isinstance(size, int) else (size, size)
        self._scale = scale
        self._ratio = ratio
        self._interp = interpolation

    def forward(self, x):
        H, W = (x.shape[0], x.shape[1]) if x.ndim == 3 else \
            (x.shape[1], x.shape[2])
        area = H * W
        for _ in range(10):
            target_area = _pyrandom.uniform(*self._scale) * area
            aspect = _pyrandom.uniform(*self._ratio)
            w = int(round((target_area * aspect) ** 0.5))
            h = int(round((target_area / aspect) ** 0.5))
            if w <= W and h <= H:
                x0 = _pyrandom.randint(0, W - w)
                y0 = _pyrandom.randint(0, H - h)
                crop = _img.crop(x, x=x0, y=y0, width=w, height=h)
                return _img.resize(crop, size=self._size, interp=self._interp)
        return _img.resize(_img.center_crop(x, size=(min(W, H), min(W, H))),
                           size=self._size, interp=self._interp)


class RandomFlipLeftRight(HybridBlock):
    def hybrid_forward(self, F, x):
        return _img.random_flip_left_right(x)


class RandomFlipTopBottom(HybridBlock):
    def hybrid_forward(self, F, x):
        return _img.random_flip_top_bottom(x)


class RandomBrightness(Block):
    def __init__(self, brightness):
        super().__init__()
        self._b = brightness

    def forward(self, x):
        alpha = 1.0 + _pyrandom.uniform(-self._b, self._b)
        return _img.brightness(x, alpha=alpha)


class RandomContrast(Block):
    def __init__(self, contrast):
        super().__init__()
        self._c = contrast

    def forward(self, x):
        alpha = 1.0 + _pyrandom.uniform(-self._c, self._c)
        return _img.contrast(x, alpha=alpha)


class RandomSaturation(Block):
    def __init__(self, saturation):
        super().__init__()
        self._s = saturation

    def forward(self, x):
        alpha = 1.0 + _pyrandom.uniform(-self._s, self._s)
        return _img.saturation(x, alpha=alpha)


class RandomHue(Block):
    def __init__(self, hue):
        super().__init__()
        self._h = hue

    def forward(self, x):
        alpha = _pyrandom.uniform(-self._h, self._h)
        return _img.hue(x, alpha=alpha)


class RandomColorJitter(Block):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        super().__init__()
        self._transforms = []
        if brightness:
            self._transforms.append(RandomBrightness(brightness))
        if contrast:
            self._transforms.append(RandomContrast(contrast))
        if saturation:
            self._transforms.append(RandomSaturation(saturation))
        if hue:
            self._transforms.append(RandomHue(hue))

    def forward(self, x):
        order = list(self._transforms)
        _pyrandom.shuffle(order)
        for t in order:
            x = t(x)
        return x


class RandomLighting(Block):
    """AlexNet-style PCA noise (ref: transforms.py:RandomLighting)."""

    _eigval = np.asarray([55.46, 4.794, 1.148], np.float32)
    _eigvec = np.asarray([[-0.5675, 0.7192, 0.4009],
                          [-0.5808, -0.0045, -0.8140],
                          [-0.5836, -0.6948, 0.4203]], np.float32)

    def __init__(self, alpha):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        from ....ndarray import array
        a = np.random.normal(0, self._alpha, size=(3,)).astype(np.float32)
        rgb = (self._eigvec * a * self._eigval).sum(axis=1)
        return x + array(rgb.reshape((1, 1, 3)))
