"""Vision datasets + transforms (ref: python/mxnet/gluon/data/vision/)."""
from .datasets import (MNIST, CIFAR10, CIFAR100, FashionMNIST,
                       ImageFolderDataset, ImageRecordDataset)
from . import transforms

__all__ = ["MNIST", "FashionMNIST", "CIFAR10", "CIFAR100",
           "ImageRecordDataset", "ImageFolderDataset", "transforms"]
