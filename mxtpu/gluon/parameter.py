"""Parameter & ParameterDict (ref: python/mxnet/gluon/parameter.py — deferred shape
inference, grad_req, per-device copies, row_sparse pull hooks).

TPU-native notes: there are no per-device parameter copies to manage — replication /
sharding across the mesh is expressed with jax.sharding on the single logical value
(SURVEY §2.3 "→ TPU"); ``data()`` returns the one NDArray regardless of ctx.
"""
from __future__ import annotations

import jax.numpy as jnp

from .. import initializer as init_mod
from ..base import MXNetError, current_context
from ..ndarray import NDArray
from ..ndarray.ndarray import _as_jax_dtype

__all__ = ["Parameter", "Constant", "ParameterDict", "DeferredInitializationError"]


import threading


class _HybridTrace(threading.local):
    """Active CachedOp trace (mxtpu/gluon/block.py): while a hybridized block is
    being traced, Parameter.data() returns the tracer-backed NDArray for the
    parameter instead of its concrete value, and mutable aux state (BatchNorm
    moving stats) is redirected into ``aux_updates`` so the traced function stays
    pure — the reference instead mutates aux NDArrays inside kernels."""

    def __init__(self):
        self.stack = []


_TRACE = _HybridTrace()


class _TraceFrame:
    def __init__(self):
        self.param_map = {}   # Parameter -> tracer NDArray
        self.aux_updates = {}  # Parameter -> new tracer value (jax array)
        self.extra_params = []  # params discovered during trace, order of first use


def _active_trace():
    return _TRACE.stack[-1] if _TRACE.stack else None


class DeferredInitializationError(MXNetError):
    """Parameter accessed before shape known (ref: parameter.py:DeferredInitializationError)."""


class Parameter:
    """A weight/bias/aux tensor owned by Blocks (ref: gluon/parameter.py:Parameter)."""

    def __init__(self, name, grad_req="write", shape=None, dtype="float32",
                 lr_mult=1.0, wd_mult=1.0, init=None, allow_deferred_init=False,
                 differentiable=True, stype="default", grad_stype="default"):
        self.name = name
        self._grad_req = grad_req if differentiable else "null"
        if isinstance(shape, int):
            shape = (shape,)
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.init = init
        self._allow_deferred_init = allow_deferred_init
        self._differentiable = differentiable
        self._stype = stype
        self._grad_stype = grad_stype
        self._data = None  # NDArray
        self._deferred_init = None
        self._trainer = None

    def __repr__(self):
        return "Parameter %s (shape=%s, dtype=%s)" % (self.name, self.shape, self.dtype)

    @property
    def grad_req(self):
        return self._grad_req

    @grad_req.setter
    def grad_req(self, req):
        self._grad_req = req
        if self._data is not None:
            if req == "null":
                self._data._grad = None
                self._data._grad_req = "null"
            else:
                self._data.attach_grad(req)

    # ------------------------------------------------------------ initialize
    def initialize(self, init=None, ctx=None, default_init=None, force_reinit=False):
        default_init = default_init or init_mod.Uniform()
        if self._data is not None and not force_reinit:
            return
        if self.shape is None or any(s == 0 for s in self.shape):
            if self._allow_deferred_init:
                self._deferred_init = (init, default_init)
                return
            raise MXNetError("Cannot initialize Parameter %s: unknown shape %s"
                             % (self.name, self.shape))
        self._finish_init(init, default_init)

    def _finish_init(self, init, default_init):
        data = NDArray(jnp.zeros(self.shape, _as_jax_dtype(self.dtype)))
        chosen = init or self.init
        if chosen is not None:
            # reference mechanism (gluon/parameter.py _finish_deferred_init):
            # an explicitly-chosen initializer rides the InitDesc attrs and
            # the dispatcher forces it through _init_weight — otherwise the
            # name dispatch would send e.g. bias_initializer=Constant(3)
            # through the *bias → zeros rule and silently ignore it
            desc = init_mod.InitDesc(self.name, attrs={"__init__": chosen})
        else:
            desc = init_mod.InitDesc(self.name)
        init_mod.create(default_init)(desc, data)
        self._load_init_data(data)
        self._deferred_init = None

    def _load_init_data(self, data: NDArray):
        self._data = data
        if self._grad_req != "null":
            self._data.attach_grad(self._grad_req)

    def _finish_deferred_init(self):
        if self._deferred_init is None:
            raise DeferredInitializationError(
                "Parameter %s was not initialized (deferred init pending; run a "
                "forward pass or provide in_units/in_channels)" % self.name)
        init, default_init = self._deferred_init
        self._finish_init(init, default_init)

    def _shape_resolved(self, shape):
        """Fill unknown dims (deferred init) once the first forward sees real data."""
        if self.shape is None:
            self.shape = tuple(shape)
        else:
            merged = []
            for mine, given in zip(self.shape, shape):
                if mine == 0:
                    merged.append(given)
                elif given != 0 and mine != given:
                    raise MXNetError("shape mismatch for %s: %s vs %s"
                                     % (self.name, self.shape, shape))
                else:
                    merged.append(mine)
            self.shape = tuple(merged)
        if self._data is None and self._deferred_init is not None:
            self._finish_deferred_init()

    # ----------------------------------------------------------------- access
    def data(self, ctx=None) -> NDArray:
        tc = _active_trace()
        if tc is not None and self in tc.param_map:
            return tc.param_map[self]
        if self._data is None:
            if self._deferred_init is not None:
                raise DeferredInitializationError(
                    "Parameter %s deferred init not complete" % self.name)
            raise MXNetError("Parameter %s has not been initialized" % self.name)
        return self._data

    def list_data(self):
        return [self.data()]

    def grad(self, ctx=None) -> NDArray:
        d = self.data()
        if d._grad is None:
            raise MXNetError("Parameter %s has no gradient (grad_req=null)" % self.name)
        return d._grad

    def list_grad(self):
        return [self.grad()]

    def list_ctx(self):
        return [current_context()]

    def zero_grad(self):
        d = self.data()
        if d._grad is not None:
            d._grad._set_data(jnp.zeros_like(d._grad._data))

    def set_data(self, data):
        if self._data is None:
            if self.shape is None or any(s == 0 for s in self.shape):
                self._shape_resolved(data.shape)
            self._load_init_data(NDArray(data._data if isinstance(data, NDArray) else data))
        else:
            src = data._data if isinstance(data, NDArray) else data
            d = jnp.asarray(src, dtype=self._data._data.dtype)
            if d is src:
                # matching dtype aliases the caller's buffer zero-copy; the
                # fused optimizer step DONATES parameter buffers in place
                # (optimizer_fused.py), which would delete the caller's
                # array on the next Trainer.step — take our own copy
                d = d.copy()
            self._data._set_data(d)

    def _update_aux(self, new_data):
        """Write mutable aux state (moving stats). Under a hybrid trace the update
        is collected functionally; eagerly it mutates in place like the reference's
        aux-state kernels (src/operator/nn/batch_norm.cc)."""
        tc = _active_trace()
        if tc is not None:
            tc.aux_updates[self] = new_data._data if isinstance(new_data, NDArray) else new_data
        else:
            self.data()._set_data(new_data._data if isinstance(new_data, NDArray) else new_data)

    def row_sparse_data(self, row_id):
        """Pull given rows (ref: parameter.py:row_sparse_data for sparse params)."""
        d = self.data()
        rows = row_id._data.astype(jnp.int32) if isinstance(row_id, NDArray) else row_id
        from ..ndarray.sparse import RowSparseNDArray
        return RowSparseNDArray(NDArray(d._data[rows]), NDArray(rows), d.shape)

    def cast(self, dtype):
        self.dtype = dtype
        if self._data is not None:
            g = self._data._grad
            self._data = NDArray(self._data._data.astype(_as_jax_dtype(dtype)))
            if self._grad_req != "null":
                self._data.attach_grad(self._grad_req)

    def reset_ctx(self, ctx):
        pass  # single logical copy on the mesh

    def var(self):
        from ..symbol import var
        return var(self.name, shape=self.shape, dtype=self.dtype)


class Constant(Parameter):
    """Non-differentiable constant parameter (ref: parameter.py:Constant)."""

    def __init__(self, name, value):
        if not isinstance(value, NDArray):
            value = NDArray(jnp.asarray(value))
        self.value = value
        super().__init__(name, grad_req="null", shape=value.shape,
                         dtype=str(value.dtype),
                         init=init_mod.Constant(0.0), differentiable=False)
        self._load_init_data(NDArray(value._data))

    def initialize(self, *args, **kwargs):
        pass


class ParameterDict:
    """Ordered name → Parameter mapping with prefix + shared dict
    (ref: gluon/parameter.py:ParameterDict)."""

    def __init__(self, prefix="", shared=None):
        self._prefix = prefix
        self._params = {}
        self._shared = shared

    @property
    def prefix(self):
        return self._prefix

    def items(self):
        return self._params.items()

    def keys(self):
        return self._params.keys()

    def values(self):
        return self._params.values()

    def __iter__(self):
        return iter(self._params)

    def __getitem__(self, key):
        return self._params[key]

    def __contains__(self, key):
        return key in self._params

    def __repr__(self):
        s = "%s(\n" % type(self).__name__
        for p in self._params.values():
            s += "  %r\n" % p
        return s + ")"

    def get(self, name, **kwargs) -> Parameter:
        """Create-or-retrieve with prefix (ref: ParameterDict.get)."""
        name = self._prefix + name
        if name in self._params:
            param = self._params[name]
            # update unknown attrs
            for k, v in kwargs.items():
                if k == "shape" and v is not None and param.shape is not None:
                    continue
                if getattr(param, k, None) in (None, 0) and v is not None:
                    setattr(param, k, v)
            return param
        if self._shared is not None and name in self._shared:
            self._params[name] = self._shared[name]
            return self._shared[name]
        param = Parameter(name, **kwargs)
        self._params[name] = param
        return param

    def get_constant(self, name, value=None) -> Constant:
        name = self._prefix + name
        if name in self._params:
            return self._params[name]
        c = Constant(name, value)
        self._params[name] = c
        return c

    def update(self, other):
        for k, v in other.items():
            if k in self._params and self._params[k] is not v:
                raise MXNetError("duplicate parameter %s" % k)
            self._params[k] = v

    def initialize(self, init=None, ctx=None, verbose=False, force_reinit=False):
        for p in self._params.values():
            p.initialize(init=None, ctx=ctx, default_init=init or init_mod.Uniform(),
                         force_reinit=force_reinit)

    def zero_grad(self):
        for p in self._params.values():
            if p.grad_req != "null" and p._data is not None:
                p.zero_grad()

    def setattr(self, name, value):
        for p in self._params.values():
            setattr(p, name, value)

    def save(self, filename, strip_prefix=""):
        from ..ndarray import save as nd_save
        arg = {}
        for p in self._params.values():
            name = p.name
            if strip_prefix and name.startswith(strip_prefix):
                name = name[len(strip_prefix):]
            arg[name] = p.data()
        nd_save(filename, arg)

    def load(self, filename, ctx=None, allow_missing=False, ignore_extra=False,
             restore_prefix=""):
        from ..ndarray import load as nd_load
        loaded = nd_load(filename)
        # strip the checkpoint kind markers (ref: parameter.py load strips
        # the arg:/aux: prefixes written by export/save_checkpoint)
        loaded = {(k[4:] if k.startswith(("arg:", "aux:")) else k): v
                  for k, v in loaded.items()}
        loaded = {restore_prefix + k: v for k, v in loaded.items()}
        if not allow_missing:
            for name in self._params:
                if name not in loaded:
                    raise MXNetError("Parameter %s missing in file %s" % (name, filename))
        for name, v in loaded.items():
            if name not in self._params:
                if ignore_extra:
                    continue
                raise MXNetError("Parameter %s in file is not in this dict" % name)
            self._params[name].set_data(v)
