"""Checkpoint helpers, BatchEndParam, and the legacy FeedForward estimator
(ref: python/mxnet/model.py).

Format parity: ``prefix-symbol.json`` (graph) + ``prefix-%04d.params`` holding
``arg:name`` / ``aux:name`` keyed NDArrays, exactly the reference's layout
(model.py:383-413), so tooling that inspects checkpoints ports over.

FeedForward (reference model.py:451-1027) predates the Module API; it is
kept for parity as a thin estimator over :class:`mxtpu.module.Module` —
the reference's `_train_multi_device` multi-GPU executor loop collapses
into the one jit-compiled executor the Module already owns.
"""
from __future__ import annotations

import logging
import warnings
from collections import namedtuple

import numpy as np

from .base import MXNetError
from .ndarray.utils import load as nd_load, save as nd_save

__all__ = ["BatchEndParam", "save_checkpoint", "load_checkpoint",
           "FeedForward"]

BatchEndParam = namedtuple("BatchEndParams",
                           ["epoch", "nbatch", "eval_metric", "locals"])


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params,
                    remove_amp_cast=True):
    """Ref: model.py:save_checkpoint."""
    if symbol is not None:
        symbol.save("%s-symbol.json" % prefix)
    save_dict = {("arg:%s" % k): v for k, v in arg_params.items()}
    save_dict.update({("aux:%s" % k): v for k, v in aux_params.items()})
    param_name = "%s-%04d.params" % (prefix, epoch)
    nd_save(param_name, save_dict)


def load_checkpoint(prefix, epoch):
    """Ref: model.py:load_checkpoint. Returns (symbol, arg_params, aux_params)."""
    from . import symbol as sym_mod
    import os
    symbol = None
    if os.path.exists("%s-symbol.json" % prefix):
        symbol = sym_mod.load("%s-symbol.json" % prefix)
    save_dict = nd_load("%s-%04d.params" % (prefix, epoch))
    arg_params, aux_params = {}, {}
    for k, v in save_dict.items():
        tp, _, name = k.partition(":")
        if tp == "arg":
            arg_params[name] = v
        elif tp == "aux":
            aux_params[name] = v
        else:
            raise MXNetError("Invalid param file key %s" % k)
    return symbol, arg_params, aux_params


class FeedForward:
    """Legacy estimator: fit/predict/score on a symbol (ref: model.py:451).

    Deprecated in the reference in favor of Module — kept for API parity.
    One internal :class:`mxtpu.module.Module` replaces the reference's
    `_train_multi_device` per-GPU executor group (model.py:192-381).
    """

    def __init__(self, symbol, ctx=None, num_epoch=None, epoch_size=None,
                 optimizer="sgd", initializer=None, numpy_batch_size=128,
                 arg_params=None, aux_params=None, allow_extra_params=False,
                 begin_epoch=0, **kwargs):
        warnings.warn("FeedForward is deprecated. Please use Module instead.",
                      DeprecationWarning, stacklevel=2)
        from .initializer import Uniform
        from .symbol import Symbol
        if not isinstance(symbol, Symbol):
            # reference accepts sym_gen callables here; bucketing belongs
            # to BucketingModule in this framework
            raise MXNetError("sym_gen callables are BucketingModule's job; "
                             "FeedForward here takes a Symbol")
        self.symbol = symbol
        self.arg_params = arg_params
        self.aux_params = aux_params
        self.allow_extra_params = allow_extra_params
        if allow_extra_params:
            if self.arg_params:
                names = set(symbol.list_arguments())
                self.arg_params = {k: v for k, v in self.arg_params.items()
                                   if k in names}
            if self.aux_params:
                names = set(symbol.list_auxiliary_states())
                self.aux_params = {k: v for k, v in self.aux_params.items()
                                   if k in names}
        self.ctx = ctx
        self.num_epoch = num_epoch
        self.epoch_size = epoch_size
        self.optimizer = optimizer
        self.initializer = initializer if initializer is not None \
            else Uniform(0.01)
        self.numpy_batch_size = numpy_batch_size
        self.begin_epoch = begin_epoch
        self.kwargs = kwargs.copy()
        self._module = None
        # bound inference module cached per input-shape signature (the
        # reference's _pred_exec, model.py:610) so a serving loop doesn't
        # re-bind + recompile per predict() call
        self._pred_key = None
        self._pred_module = None

    # ------------------------------------------------------------ plumbing
    def _init_iter(self, X, y, is_train):
        """numpy/NDArray → NDArrayIter (ref: model.py:628-652)."""
        from .io import NDArrayIter
        from .ndarray import NDArray
        if isinstance(X, (np.ndarray, NDArray)):
            if y is None:
                if is_train:
                    raise MXNetError("y must be specified when X is numpy")
                y = np.zeros(X.shape[0])
            y = y.asnumpy() if isinstance(y, NDArray) else np.asarray(y)
            if X.shape[0] != y.shape[0]:
                raise MXNetError("data and label lengths differ")
            if y.ndim == 2 and y.shape[1] == 1:
                y = y.flatten()
            if y.ndim != 1:
                raise MXNetError("label must be 1D or 2D with 2nd dim 1")
            bs = min(X.shape[0], self.numpy_batch_size)
            if is_train:
                return NDArrayIter(X, y, bs, shuffle=True,
                                   last_batch_handle="roll_over")
            return NDArrayIter(X, y, bs, shuffle=False)
        return X

    def _init_eval_iter(self, eval_data):
        """(ref: model.py:653-672)"""
        if eval_data is None:
            return None
        if isinstance(eval_data, (tuple, list)) and len(eval_data) == 2:
            d = np.array(eval_data[0]) if isinstance(eval_data[0], list) \
                else eval_data[0]
            lbl = np.array(eval_data[1]) if isinstance(eval_data[1], list) \
                else eval_data[1]
            return self._init_iter(d, lbl, is_train=True)
        return eval_data

    def _build_module(self, data_iter):
        from .module import Module
        data_names = [x[0] for x in data_iter.provide_data]
        label_names = [x[0] for x in (data_iter.provide_label or [])]
        return Module(self.symbol, data_names=data_names,
                      label_names=label_names, context=self.ctx)

    # ------------------------------------------------------------ training
    def fit(self, X, y=None, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            logger=None, work_load_list=None, monitor=None,
            eval_end_callback=None, eval_batch_end_callback=None):
        """(ref: model.py:793-894)"""
        data = self._init_iter(X, y, is_train=True)
        eval_data = self._init_eval_iter(eval_data)
        if self.num_epoch is None:
            raise MXNetError("num_epoch must be set to fit")
        if self.epoch_size is not None:
            (logger or logging).warning(
                "epoch_size is ignored: the jit executor trains full "
                "iterator epochs")
        opt = self.optimizer
        opt_kw = dict(self.kwargs)
        mod = self._build_module(data)
        if logger is not None:
            mod.logger = logger
        mod.fit(data, eval_data=eval_data, eval_metric=eval_metric,
                epoch_end_callback=epoch_end_callback,
                batch_end_callback=batch_end_callback, kvstore=kvstore,
                optimizer=opt, optimizer_params=opt_kw,
                eval_end_callback=eval_end_callback,
                eval_batch_end_callback=eval_batch_end_callback,
                initializer=self.initializer, arg_params=self.arg_params,
                aux_params=self.aux_params, allow_missing=True,
                begin_epoch=self.begin_epoch, num_epoch=self.num_epoch,
                monitor=monitor)
        self._module = mod
        self.arg_params, self.aux_params = mod.get_params()
        return self

    # ----------------------------------------------------------- inference
    def _init_predictor(self, data_iter):
        if self.arg_params is None:
            raise MXNetError("model has no parameters: fit() or load() first")

        def _shape_of(d):
            return (d.name, tuple(d.shape)) if hasattr(d, "name") \
                else (d[0], tuple(d[1]))

        key = tuple(_shape_of(d) for d in data_iter.provide_data)
        if self._pred_key != key:
            mod = self._build_module(data_iter)
            mod.bind(data_shapes=data_iter.provide_data,
                     label_shapes=data_iter.provide_label, for_training=False)
            self._pred_key, self._pred_module = key, mod
        # (re)load params even on cache hit — fit()/load() may have
        # refreshed them since the module was bound
        self._pred_module.init_params(arg_params=self.arg_params,
                                      aux_params=self.aux_params or {},
                                      allow_missing=True, force_init=True)
        return self._pred_module

    def predict(self, X, num_batch=None, return_data=False, reset=True):
        """Forward over X; returns numpy outputs (ref: model.py:673-741)."""
        data = self._init_iter(X, y=None, is_train=False)
        if reset:
            data.reset()
        mod = self._init_predictor(data)
        if not return_data:
            res = mod.predict(data, num_batch=num_batch, reset=False)
            if isinstance(res, list):
                return [o.asnumpy() for o in res]
            return res.asnumpy()
        outputs, datas, labels = [], [], []
        for nbatch, batch in enumerate(data):
            if num_batch is not None and nbatch == num_batch:
                break
            mod.forward(batch, is_train=False)
            n = batch.data[0].shape[0] - batch.pad
            outputs.append([o.asnumpy()[:n] for o in mod.get_outputs()])
            datas.append([d.asnumpy()[:n] for d in batch.data])
            labels.append([l.asnumpy()[:n] for l in (batch.label or [])])
        num_out = len(outputs[0]) if outputs else 0
        merged = [np.concatenate([o[i] for o in outputs])
                  for i in range(num_out)]
        result = merged[0] if num_out == 1 else merged
        md = [np.concatenate([d[i] for d in datas])
              for i in range(len(datas[0]))] if datas else []
        ml = [np.concatenate([l[i] for l in labels])
              for i in range(len(labels[0]))] if labels and labels[0] else []
        return (result, md[0] if len(md) == 1 else md,
                ml[0] if len(ml) == 1 else ml)

    def score(self, X, eval_metric="acc", num_batch=None,
              batch_end_callback=None, reset=True):
        """Evaluate on X (ref: model.py:742-792)."""
        data = self._init_iter(X, y=None, is_train=False)
        if reset:
            data.reset()
        mod = self._init_predictor(data)
        res = mod.score(data, eval_metric, num_batch=num_batch,
                        batch_end_callback=batch_end_callback, reset=False)
        return res[0][1] if res else None

    # ----------------------------------------------------------- persistence
    def save(self, prefix, epoch=None):
        """(ref: model.py:895-917)"""
        if epoch is None:
            epoch = self.num_epoch
        assert epoch is not None
        save_checkpoint(prefix, epoch, self.symbol, self.arg_params or {},
                        self.aux_params or {})

    @staticmethod
    def load(prefix, epoch, ctx=None, **kwargs):
        """(ref: model.py:918-948)"""
        symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        return FeedForward(symbol, ctx=ctx, arg_params=arg_params,
                           aux_params=aux_params, begin_epoch=epoch, **kwargs)

    @staticmethod
    def create(symbol, X, y=None, ctx=None, num_epoch=None, epoch_size=None,
               optimizer="sgd", initializer=None, eval_data=None,
               eval_metric="acc", epoch_end_callback=None,
               batch_end_callback=None, kvstore="local", logger=None,
               work_load_list=None, eval_end_callback=None,
               eval_batch_end_callback=None, **kwargs):
        """Functional-style fit (ref: model.py:949-1027)."""
        model = FeedForward(symbol, ctx=ctx, num_epoch=num_epoch,
                            epoch_size=epoch_size, optimizer=optimizer,
                            initializer=initializer, **kwargs)
        model.fit(X, y, eval_data=eval_data, eval_metric=eval_metric,
                  epoch_end_callback=epoch_end_callback,
                  batch_end_callback=batch_end_callback, kvstore=kvstore,
                  logger=logger, work_load_list=work_load_list,
                  eval_end_callback=eval_end_callback,
                  eval_batch_end_callback=eval_batch_end_callback)
        return model
