"""Checkpoint helpers + BatchEndParam (ref: python/mxnet/model.py).

Format parity: ``prefix-symbol.json`` (graph) + ``prefix-%04d.params`` holding
``arg:name`` / ``aux:name`` keyed NDArrays, exactly the reference's layout
(model.py:383-413), so tooling that inspects checkpoints ports over.
"""
from __future__ import annotations

from collections import namedtuple

from .base import MXNetError
from .ndarray.utils import load as nd_load, save as nd_save

__all__ = ["BatchEndParam", "save_checkpoint", "load_checkpoint"]

BatchEndParam = namedtuple("BatchEndParams",
                           ["epoch", "nbatch", "eval_metric", "locals"])


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params,
                    remove_amp_cast=True):
    """Ref: model.py:save_checkpoint."""
    if symbol is not None:
        symbol.save("%s-symbol.json" % prefix)
    save_dict = {("arg:%s" % k): v for k, v in arg_params.items()}
    save_dict.update({("aux:%s" % k): v for k, v in aux_params.items()})
    param_name = "%s-%04d.params" % (prefix, epoch)
    nd_save(param_name, save_dict)


def load_checkpoint(prefix, epoch):
    """Ref: model.py:load_checkpoint. Returns (symbol, arg_params, aux_params)."""
    from . import symbol as sym_mod
    import os
    symbol = None
    if os.path.exists("%s-symbol.json" % prefix):
        symbol = sym_mod.load("%s-symbol.json" % prefix)
    save_dict = nd_load("%s-%04d.params" % (prefix, epoch))
    arg_params, aux_params = {}, {}
    for k, v in save_dict.items():
        tp, _, name = k.partition(":")
        if tp == "arg":
            arg_params[name] = v
        elif tp == "aux":
            aux_params[name] = v
        else:
            raise MXNetError("Invalid param file key %s" % k)
    return symbol, arg_params, aux_params
