"""Base types for the TPU-native framework.

Mirrors the role of the reference's ``include/mxnet/base.h`` + ``python/mxnet/base.py``
(Context, dtype codes, error type), re-designed for JAX/PJRT: a Context names a PJRT
device (TPU chip or host CPU) instead of a CUDA device, and there is no ctypes FFI —
the "C API" equivalent is the in-process runtime in :mod:`mxtpu.runtime`.
"""
from __future__ import annotations

import os
import threading

import numpy as _np

__all__ = [
    "MXNetError", "Context", "cpu", "gpu", "tpu", "current_context",
    "DTYPE_TO_CODE", "CODE_TO_DTYPE", "np_dtype", "numeric_types", "string_types",
]

# ref: python/mxnet/base.py numeric_types/string_types
numeric_types = (float, int, _np.generic)
string_types = (str,)


class MXNetError(RuntimeError):
    """Error raised by the framework (ref: python/mxnet/base.py:MXNetError)."""


# dtype integer codes, kept wire-compatible with the reference's mshadow TypeFlag
# (3rdparty/mshadow usage at include/mxnet/ndarray.h / python/mxnet/base.py _DTYPE_NP_TO_MX)
DTYPE_TO_CODE = {
    "float32": 0,
    "float64": 1,
    "float16": 2,
    "uint8": 3,
    "int32": 4,
    "int8": 5,
    "int64": 6,
    # TPU-native additions (no reference counterpart):
    "bfloat16": 7,
    "bool": 8,
}
CODE_TO_DTYPE = {v: k for k, v in DTYPE_TO_CODE.items()}


def np_dtype(dtype):
    """Canonicalize a dtype-ish value to a string name (bfloat16-aware)."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        name = dtype
    else:
        name = _np.dtype(dtype).name if not _is_bfloat16(dtype) else "bfloat16"
    if name == "bfloat16":
        return "bfloat16"
    return _np.dtype(name).name


def _is_bfloat16(dtype) -> bool:
    try:
        return "bfloat16" in str(dtype)
    except Exception:  # pragma: no cover
        return False


class Context:
    """A device context (ref: python/mxnet/context.py:Context).

    Device types:
      * ``cpu``  — host CPU (JAX cpu backend)
      * ``tpu``  — a TPU chip (the accelerator; primary device of this framework)
      * ``gpu``  — alias for the default accelerator so reference-era scripts that
        say ``mx.gpu(0)`` run unmodified on TPU.

    Unlike the reference there is no per-device worker-thread pool to configure:
    async dispatch and per-device ordering are provided by PJRT streams
    (ref engine: src/engine/threaded_engine_perdevice.cc — subsumed by PJRT).
    """

    devtype2str = {1: "cpu", 2: "gpu", 3: "cpu_pinned", 5: "cpu_shared", 6: "tpu"}
    devstr2type = {v: k for k, v in devtype2str.items()}
    _default_ctx = threading.local()

    def __init__(self, device_type, device_id: int = 0):
        if isinstance(device_type, Context):
            self.device_typeid = device_type.device_typeid
            self.device_id = device_type.device_id
        elif isinstance(device_type, str):
            self.device_typeid = Context.devstr2type[device_type]
            self.device_id = device_id
        else:
            self.device_typeid = device_type
            self.device_id = device_id

    @property
    def device_type(self) -> str:
        return Context.devtype2str[self.device_typeid]

    def __eq__(self, other):
        return (
            isinstance(other, Context)
            and self.device_typeid == other.device_typeid
            and self.device_id == other.device_id
        )

    def __hash__(self):
        return hash((self.device_typeid, self.device_id))

    def __repr__(self):
        return "%s(%d)" % (self.device_type, self.device_id)

    __str__ = __repr__

    # -- PJRT resolution -------------------------------------------------
    def jax_device(self):
        """Resolve this Context to a concrete PJRT device.

        ``tpu``/``gpu`` map to the default accelerator backend; if the process
        is running CPU-only (e.g. the virtual multi-device test mesh), they
        degrade to CPU devices so reference-style scripts still run.
        """
        import jax

        dt = self.device_type
        if dt in ("cpu", "cpu_pinned", "cpu_shared"):
            try:
                devs = jax.devices("cpu")
            except RuntimeError:
                devs = jax.devices()
        else:  # tpu / gpu -> default accelerator backend
            devs = jax.devices()
        return devs[self.device_id % len(devs)]

    def empty_cache(self):
        """Release cached device memory (ref: MXStorageEmptyCache). PJRT pools
        internally; provided for API parity."""

    def __enter__(self):
        if not hasattr(Context._default_ctx, "contexts"):
            Context._default_ctx.contexts = [Context("tpu", 0)]
        Context._default_ctx.contexts.append(self)
        return self

    def __exit__(self, *args):
        Context._default_ctx.contexts.pop()


def cpu(device_id: int = 0) -> Context:
    return Context("cpu", device_id)


def gpu(device_id: int = 0) -> Context:
    """Alias of :func:`tpu` for reference-script compatibility."""
    return Context("gpu", device_id)


def tpu(device_id: int = 0) -> Context:
    return Context("tpu", device_id)


def current_context() -> Context:
    if not hasattr(Context._default_ctx, "contexts"):
        Context._default_ctx.contexts = [Context("tpu", 0)]
    return Context._default_ctx.contexts[-1]


def num_gpus() -> int:
    """Number of accelerator devices visible (ref: mx.context.num_gpus)."""
    import jax

    try:
        return len([d for d in jax.devices() if d.platform != "cpu"])
    except RuntimeError:
        return 0


def getenv(name: str, default):
    """Typed env-var lookup (ref: dmlc::GetEnv; catalog docs/faq/env_var.md)."""
    val = os.environ.get(name)
    if val is None:
        return default
    if isinstance(default, bool):
        return val.lower() in ("1", "true", "yes", "on")
    return type(default)(val)
